"""Unified observability spine (PR 10) + tail-latency diagnostics (PR 14).

- :mod:`.trace` — request-/step-scoped hierarchical span tracer; Chrome-trace
  (Perfetto) + JSONL export; cross-process trace-id join over the subprocess
  serving pipe;
- :mod:`.metrics` — bounded process-wide registry (counters / gauges /
  fixed-log-bucket histograms) with ONE declared tag schema, MonitorMaster as
  an export backend and Prometheus text exposition plus the HTTP status plane
  (``/metrics`` / ``/statusz`` / ``/healthz``);
- :mod:`.schema` — the declared tag table + the emission-site lint;
- :mod:`.profiler` — on-demand ``jax.profiler`` capture of N steps/chunks,
  armed by config or ``SIGUSR2``;
- :mod:`.attribution` — per-request latency decomposition (span tree → named
  phases summing to e2e) and the p50-vs-p99 phase-share breakdown;
- :mod:`.flight` — bounded tail-sampling flight recorder (full span trees for
  slow/failed/retried/shed/deadline-missed requests + a 1-in-N sample),
  control-plane decision journal, Perfetto-loadable dump bundles (on demand,
  ``SIGUSR1``, router drain, anomaly trips);
- :mod:`.anomaly` — EWMA+MAD scoring over registry streams; a trip dumps the
  flight bundle and arms the XLA profiler for the next K ticks.
"""

from . import schema
from .anomaly import AnomalyConfig, AnomalyDetector, get_detector
from .attribution import attribute, phase_breakdown
from .flight import (FlightConfig, FlightRecorder, get_recorder,
                     install_recorder)
from .flight import journal as flight_journal
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      get_registry, record_events, start_metrics_server)
from .profiler import ProfilerCapture, configure_capture, get_capture
from .profiler import tick as profiler_tick
from .trace import (CAT_ROUTER, CAT_SERVING, CAT_TRAIN, OpenSpan, SpanContext,
                    Tracer, chrome_events_from, get_tracer)

__all__ = [
    "schema", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "record_events", "start_metrics_server",
    "ProfilerCapture", "configure_capture", "get_capture", "profiler_tick",
    "CAT_ROUTER", "CAT_SERVING", "CAT_TRAIN", "OpenSpan", "SpanContext",
    "Tracer", "get_tracer", "chrome_events_from",
    "attribute", "phase_breakdown",
    "FlightConfig", "FlightRecorder", "get_recorder", "install_recorder",
    "flight_journal",
    "AnomalyConfig", "AnomalyDetector", "get_detector",
]
