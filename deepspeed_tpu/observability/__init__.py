"""Unified observability spine (PR 10).

- :mod:`.trace` — request-/step-scoped hierarchical span tracer; Chrome-trace
  (Perfetto) + JSONL export; cross-process trace-id join over the subprocess
  serving pipe;
- :mod:`.metrics` — bounded process-wide registry (counters / gauges /
  fixed-log-bucket histograms) with ONE declared tag schema, MonitorMaster as
  an export backend and Prometheus text exposition (``/metrics``);
- :mod:`.schema` — the declared tag table + the emission-site lint;
- :mod:`.profiler` — on-demand ``jax.profiler`` capture of N steps/chunks,
  armed by config or ``SIGUSR2``.
"""

from . import schema
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      get_registry, record_events, start_metrics_server)
from .profiler import ProfilerCapture, configure_capture, get_capture
from .profiler import tick as profiler_tick
from .trace import (CAT_ROUTER, CAT_SERVING, CAT_TRAIN, OpenSpan, SpanContext,
                    Tracer, get_tracer)

__all__ = [
    "schema", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "record_events", "start_metrics_server",
    "ProfilerCapture", "configure_capture", "get_capture", "profiler_tick",
    "CAT_ROUTER", "CAT_SERVING", "CAT_TRAIN", "OpenSpan", "SpanContext",
    "Tracer", "get_tracer",
]
