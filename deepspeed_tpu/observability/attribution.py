"""Per-request latency attribution: span tree → named-phase decomposition.

The PR 10 tracer records *what* happened to a request (``request`` →
``attempt{replica}`` → ``replica_request`` → ``queue_wait`` /
``prefix_lookup`` / ``restore_prefix`` / ``prefill`` / ``decode_chunk×N`` /
``retire``); this module answers *where the time went*: every completed
request's end-to-end latency is decomposed into a fixed set of named phases
whose sum equals the e2e latency **by construction** (the phases partition the
root span's wall window — the tested identity is sum(phases) == e2e within
1%, the slack covering only float accumulation):

- ``queue``    — admission-queue wait: the ``queue_wait`` spans plus any
  uncovered time before the first replica-side work begins (router-level
  queueing happens before an ``attempt`` span exists);
- ``admission`` — admission-time work: prefix-cache trie lookups;
- ``kv_restore`` — prefix-slab restore / page-bind time inside a cache-hit
  prefill;
- ``prefill``  — prefill dispatch minus the restore share;
- ``decode``   — decode-chunk compute (the slot-batch dispatches this request
  participated in);
- ``gap``      — inter-chunk scheduling gap: time inside the serving window
  covered by no span (co-batch waits, pump latency, harvest);
- ``retry_lost`` — every second spent inside an abandoned lane: the full
  subtree of any evicted/failed ``attempt`` and any ``replica_request``
  force-closed ``state=abandoned`` when its replica was killed. This is the
  serving-pipeline analogue of T3's attribution-of-overlap argument — the
  tail is usually not "decode got slow" but "a whole lane was thrown away".

Spans here are the tracer's finished-span dicts (``ts``/``dur`` in wall µs).
The flight recorder feeds each completed trace through :func:`attribute` and
aggregates rows into the "where did the p99 go" breakdown
(:func:`phase_breakdown`): phase *shares* at p50 vs p99 — the BENCH JSON's
answer to why the tail is shaped the way it is.
"""

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: the fixed phase vocabulary, in classification-priority order (earlier
#: phases claim overlapping wall time first; ``gap`` is the residual)
PHASES = ("queue", "admission", "kv_restore", "prefill", "decode",
          "gap", "retry_lost")

#: registry tags for the per-phase histograms (declared in ``schema.TAGS``)
PHASE_TAGS = {
    "queue": "latency/phase/queue_ms",
    "admission": "latency/phase/admission_ms",
    "kv_restore": "latency/phase/kv_restore_ms",
    "prefill": "latency/phase/prefill_ms",
    "decode": "latency/phase/decode_ms",
    "gap": "latency/phase/gap_ms",
    "retry_lost": "latency/phase/retry_lost_ms",
}

E2E_TAG = "latency/e2e_ms"

#: span names that root a request-scoped trace (``request`` = router front
#: door; ``replica_request`` roots the single-scheduler path)
ROOT_NAMES = ("request", "replica_request")

#: attempt outcomes / lane states that mark a subtree as thrown-away work
_FAILED_ATTEMPT_OUTCOMES = ("evicted", "dispatch_error", "error")
_FAILED_LANE_STATES = ("abandoned", "evicted")

Interval = Tuple[float, float]

#: tracer span name → phase (spans with other names only move ``first_work``)
_NAME_TO_PHASE = {"queue_wait": "queue", "prefix_lookup": "admission",
                  "restore_prefix": "kv_restore", "prefill": "prefill",
                  "suffix_prefill": "prefill", "bucket_prefill": "prefill",
                  "decode_chunk": "decode"}


def _merge(intervals: Sequence[Interval]) -> List[Interval]:
    """Sorted disjoint union."""
    out: List[Interval] = []
    for lo, hi in sorted(intervals):
        if hi <= lo:
            continue
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _subtract(intervals: Sequence[Interval],
              covered: Sequence[Interval]) -> List[Interval]:
    """``intervals`` minus ``covered`` (both sorted disjoint)."""
    out: List[Interval] = []
    for lo, hi in intervals:
        cur = lo
        for clo, chi in covered:
            if chi <= cur:
                continue
            if clo >= hi:
                break
            if clo > cur:
                out.append((cur, clo))
            cur = max(cur, chi)
            if cur >= hi:
                break
        if cur < hi:
            out.append((cur, hi))
    return out


def _length(intervals: Sequence[Interval]) -> float:
    return sum(hi - lo for lo, hi in intervals)


def _clamp(lo: float, hi: float, t0: float, t1: float) -> Optional[Interval]:
    lo, hi = max(lo, t0), min(hi, t1)
    return (lo, hi) if hi > lo else None


def find_root(spans: Sequence[Dict]) -> Optional[Dict]:
    """The request-scoped root span of a finished trace: a parentless span
    named ``request`` (router front door preferred) or ``replica_request``
    (single-scheduler path). None when the trace is not request-shaped."""
    roots = [s for s in spans
             if not s.get("parent_id") and s.get("name") in ROOT_NAMES]
    if not roots:
        return None
    for s in roots:
        if s["name"] == "request":
            return s
    return roots[0]


def _failed_subtree_ids(spans: Sequence[Dict]) -> set:
    """Span ids belonging to thrown-away lanes: evicted/errored ``attempt``
    subtrees and ``state=abandoned``/``evicted`` ``replica_request`` subtrees
    (a killed replica's force-closed lane), including every descendant."""
    seeds = []
    for s in spans:
        name = s.get("name")
        if name != "attempt" and name != "replica_request":
            continue
        attrs = s.get("attrs") or {}
        if name == "attempt" \
                and attrs.get("outcome") in _FAILED_ATTEMPT_OUTCOMES:
            seeds.append(s)
        elif name == "replica_request" \
                and attrs.get("state") in _FAILED_LANE_STATES:
            seeds.append(s)
    if not seeds:
        return set()    # healthy trace: skip the child-map build entirely
    children: Dict[str, List[Dict]] = {}
    for s in spans:
        pid = s.get("parent_id")
        if pid:
            children.setdefault(pid, []).append(s)
    failed = set()
    stack = list(seeds)
    while stack:
        s = stack.pop()
        sid = s.get("span_id")
        if sid in failed:
            continue
        failed.add(sid)
        stack.extend(children.get(sid, ()))
    return failed


def attribute(spans: Sequence[Dict]) -> Optional[Dict]:
    """Decompose one finished trace into the named phases.

    Returns an attribution row ``{"trace_id", "request_id", "state", "e2e_ms",
    "phases": {phase: ms}, "tokens", "attempts", "retried"}`` or None when the
    trace has no request root. The phases PARTITION the root window, so
    ``sum(phases.values()) == e2e_ms`` up to float accumulation — the
    attribution identity the tests pin."""
    root = find_root(spans)
    if root is None:
        return None
    t0 = float(root["ts"])
    t1 = t0 + float(root["dur"])
    failed = _failed_subtree_ids(spans)

    by_phase: Dict[str, List[Interval]] = {p: [] for p in PHASES}
    first_work = t1
    for s in spans:
        if s is root:
            continue
        iv = _clamp(float(s["ts"]), float(s["ts"]) + float(s["dur"]), t0, t1)
        if iv is None:
            continue
        if failed and s.get("span_id") in failed:
            by_phase["retry_lost"].append(iv)
            continue
        phase = _NAME_TO_PHASE.get(s.get("name"))
        if phase is None:
            if s.get("name") == "replica_request":
                first_work = min(first_work, iv[0])
            continue
        by_phase[phase].append(iv)
        first_work = min(first_work, iv[0])

    # priority-ordered disjoint coverage: a restore second is a restore
    # second even though the prefill span covers it too. Empty phases are
    # skipped — this runs once per completed request on the serving host.
    priority = ("retry_lost", "kv_restore", "admission", "queue",
                "decode", "prefill")
    covered: List[Interval] = []
    phases_ms = {p: 0.0 for p in PHASES}
    for phase in priority:
        if not by_phase[phase]:
            continue
        ivs = _subtract(_merge(by_phase[phase]), covered)
        phases_ms[phase] = _length(ivs) / 1e3
        covered = _merge(list(covered) + ivs)

    # residual: uncovered time before the first replica-side work is router
    # queueing (no span exists for it — the attempt hasn't been dispatched);
    # uncovered time after it is inter-chunk scheduling gap
    uncovered = _subtract([(t0, t1)], covered)
    for lo, hi in uncovered:
        pre = min(hi, max(lo, first_work))
        phases_ms["queue"] += (pre - lo) / 1e3
        phases_ms["gap"] += (hi - pre) / 1e3

    attrs = root.get("attrs") or {}
    return {
        "trace_id": root.get("trace_id"),
        "request_id": attrs.get("request_id"),
        "state": attrs.get("state"),
        "e2e_ms": (t1 - t0) / 1e3,
        "phases": phases_ms,
        "tokens": attrs.get("tokens"),
        "attempts": attrs.get("attempts", 1),
        "retried": attrs.get("retried", 0),
        "failed_lanes": len(failed),
    }


def phase_breakdown(rows: Sequence[Dict]) -> Dict:
    """The "where did the p99 go" aggregate: phase *shares* of e2e at p50 vs
    p99. The p50 group is the typical half (e2e <= median), the p99 group the
    tail (e2e >= p99, at least the slowest request); each group's share is
    sum(phase) / sum(e2e) over its members, so shares sum to ~1 per group."""
    rows = [r for r in rows if r and r.get("e2e_ms")]
    if not rows:
        return {"requests": 0, "e2e_ms_p50": None, "e2e_ms_p99": None,
                "p50_shares": None, "p99_shares": None}
    e2es = np.asarray([r["e2e_ms"] for r in rows], dtype=float)
    p50, p99 = float(np.percentile(e2es, 50)), float(np.percentile(e2es, 99))
    p50_rows = [r for r in rows if r["e2e_ms"] <= p50] or rows
    p99_rows = [r for r in rows if r["e2e_ms"] >= p99] \
        or [max(rows, key=lambda r: r["e2e_ms"])]

    def shares(group):
        total = sum(r["e2e_ms"] for r in group)
        if total <= 0:
            return {p: 0.0 for p in PHASES}
        return {p: sum(r["phases"][p] for r in group) / total for p in PHASES}

    return {"requests": len(rows), "e2e_ms_p50": p50, "e2e_ms_p99": p99,
            "p50_shares": shares(p50_rows), "p99_shares": shares(p99_rows)}
