"""``ds-tpu-top``: a small polling terminal view over ``/statusz``.

The live status plane (``observability.metrics.start_metrics_server`` +
``inference/serving/server.make_status_provider``) publishes one JSON
document; this renders it as a refreshing terminal frame — replica health and
outstanding work, the degradation rung, paged-KV pressure, prefix hit rate,
the fleet KV economy (hit rate, spill/promote counters, prefill tokens
skipped), the last autoscale decisions, recent anomaly trips, and the flight
recorder's retention stats. ``--once`` prints a single frame (scripts/tests);
otherwise the frame redraws every ``--interval`` seconds until interrupted.
"""

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional


def fetch_status(host: str, port: int, timeout: float = 5.0) -> Dict:
    url = f"http://{host}:{port}/statusz"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


_RUNG_NAMES = {0: "HEALTHY", 1: "DEFER_LOW", 2: "SHED_INFEASIBLE",
               3: "ADMISSION_CLOSED"}


def _fmt(v, nd: int = 2) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render(doc: Dict) -> str:
    """One status frame as a multi-line string."""
    lines: List[str] = []
    if doc.get("starting"):
        return "ds-tpu-top: server starting (no frontend yet)\n"
    kind = doc.get("kind", "?")
    rung = doc.get("degradation_rung")
    head = [f"ds-tpu-top  [{kind}]",
            time.strftime("%H:%M:%S", time.localtime(doc.get("t",
                                                             time.time())))]
    if rung is not None:
        head.append(f"rung={doc.get('degradation_rung_name', _RUNG_NAMES.get(rung, rung))}")
    if doc.get("draining"):
        head.append("DRAINING")
    lines.append("  ".join(head))
    lines.append(f"queue={_fmt(doc.get('queue_depth'))}"
                 + (f"  occupancy={_fmt(doc.get('slot_occupancy'))}"
                    if "slot_occupancy" in doc else "")
                 + (f"  prefix_hit={_fmt(doc.get('prefix_hit_rate'))}"
                    if "prefix_hit_rate" in doc else ""))
    if doc.get("replicas"):
        lines.append("replicas:")
        for r in doc["replicas"]:
            flags = " retiring" if r.get("retiring") else ""
            if "pid" in r:       # hosted replica: child process + respawns
                flags += f" pid={r['pid']} restarts={r.get('restarts', 0)}"
            lines.append(f"  #{r['id']:<3} {r['health']:<10} "
                         f"outstanding={r['outstanding']:<4} "
                         f"running={r['running']:<3} queued={r['queued']}"
                         f"{flags}")
    h = doc.get("hosts")
    if h:
        pinned = h.get("pinned") or []
        lines.append(f"hosts: restarts={h.get('restarts_total')}"
                     + (f"  pinned={pinned}" if pinned else ""))
    c = doc.get("counters") or {}
    if c:
        lines.append("counters: " + "  ".join(f"{k}={v}"
                                              for k, v in sorted(c.items())))
    p = doc.get("pages")
    if p:
        lines.append(f"pages: in_use={_fmt(p.get('pages_in_use'), 0)}"
                     f"/{_fmt(p.get('total_pages'), 0)}  "
                     f"fragmentation={_fmt(p.get('page_fragmentation'))}  "
                     f"shared={_fmt(p.get('prefix_shared_pages'), 0)}")
    kv = doc.get("kv_economy")
    if kv:
        lines.append(
            f"kv: fleet_hit={_fmt(kv.get('fleet_hit_rate'))}  "
            f"prefill_skipped={_fmt(kv.get('prefill_tokens_skipped'), 0)}tok  "
            f"spills={_fmt(kv.get('spills_total'), 0)}  "
            f"promotes={_fmt(kv.get('promotions_total'), 0)}  "
            f"spilled_mb={_fmt((kv.get('spilled_bytes') or 0) / 2**20, 1)}  "
            f"routed={_fmt(kv.get('prefix_routed'), 0)}")
    sp = doc.get("spec")
    if sp:
        lines.append(f"spec: accept={_fmt(sp.get('acceptance_rate'))}  "
                     f"accepted={_fmt(sp.get('accepted'), 0)}"
                     f"/{_fmt(sp.get('proposed'), 0)}  "
                     f"passes/tok={_fmt(sp.get('passes_per_token'))}")
    a = doc.get("autoscale")
    if a:
        lines.append(f"autoscale: target={a.get('target_replicas')} "
                     f"ups={a.get('scale_ups')} downs={a.get('scale_downs')}")
        for d in (a.get("last_decisions") or [])[-3:]:
            lines.append(f"  {d.get('action'):<5} replica={d.get('replica')} "
                         f"queue={d.get('queue_depth')} "
                         f"ttft_p95={_fmt(d.get('ttft_p95_ms'))} "
                         f"occ={_fmt(d.get('occupancy'))}")
    an = doc.get("anomalies")
    if an:
        lines.append(f"anomalies: trips={an.get('trips')}")
        for t in (an.get("recent") or [])[-3:]:
            lines.append(f"  {t.get('signal')} value={_fmt(t.get('value'))} "
                         f"score={_fmt(t.get('score'), 1)} "
                         f"(threshold {_fmt(t.get('threshold'), 1)})")
    f = doc.get("flight")
    if f:
        lines.append(f"flight: retained={f.get('retained_traces')} trace(s) "
                     f"/ {f.get('retained_spans')} span(s)  "
                     f"dumps={f.get('dumps')}  "
                     f"slow_bar_ms={_fmt(f.get('slow_bar_ms'), 1)}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ds-tpu-top",
        description="polling terminal view over a deepspeed-serve /statusz")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True,
                    help="the --metrics-port of the serve process")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (no screen clearing)")
    args = ap.parse_args(argv)
    try:
        while True:
            try:
                doc = fetch_status(args.host, args.port)
                frame = render(doc)
            except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
                frame = f"ds-tpu-top: {args.host}:{args.port} unreachable " \
                        f"({type(e).__name__}: {e})\n"
                if args.once:
                    sys.stdout.write(frame)
                    return 1
            if args.once:
                sys.stdout.write(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame)
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
