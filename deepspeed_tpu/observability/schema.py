"""The ONE declared metric-tag schema.

Every ``write_events`` / registry emission in the tree publishes tags declared
here — ``serving/*`` (scheduler telemetry), ``router/*`` (multi-replica
router), ``Train/*`` (training engine + collective spans), ``inference/*``
(single-call generate + weight-quant audit). The registry consults this table
for each tag's instrument kind (counter / gauge / histogram) and the tag-lint
test (``tests/unit/observability``) walks every emission site in the source
tree and asserts each literal tag resolves to exactly one declaration —
the guard against the pre-PR-10 drift where ``serving/``, ``router/`` and
``Train/Comm/`` each invented tag names privately.

Templated tags use ``{i}`` for a per-replica integer segment
(``router/replica{i}/health`` matches ``router/replica3/health``); emission
sites that build them with f-strings lint as ``*`` wildcards against the same
pattern.
"""

import re
from typing import Dict, Iterator, List, Optional, Tuple

COUNTER = "counter"      # cumulative total; emissions carry the running value
GAUGE = "gauge"          # last-write-wins sampled value
HISTOGRAM = "histogram"  # per-event observation into fixed log buckets

#: tag pattern -> (kind, help text). THE schema: one entry per published tag.
TAGS: Dict[str, Tuple[str, str]] = {
    # ------------------------------------------------- serving (per scheduler)
    "serving/ttft_ms": (HISTOGRAM, "queue wait + prefill per finished request"),
    "serving/tpot_ms": (HISTOGRAM, "seconds-per-token (ms) per finished request"),
    "serving/tokens_per_sec": (GAUGE, "decode throughput per chunk"),
    "serving/queue_depth": (GAUGE, "admission queue depth per scheduler tick"),
    "serving/slot_occupancy": (GAUGE, "fraction of KV slots in use per tick"),
    "serving/completed_total": (COUNTER, "requests finished"),
    "serving/rejected_total": (COUNTER, "requests rejected (backpressure)"),
    "serving/prefix_hit_rate": (GAUGE, "admission-level prefix-cache hit rate"),
    "serving/prefix_cached_bytes": (GAUGE, "resident prefix-slab bytes"),
    "serving/prefix_evicted_total": (COUNTER, "prefix-cache LRU evictions"),
    # ---------------------------------------- tiered prefix cache (PR 19)
    "serving/prefix_spilled_bytes": (GAUGE, "host-RAM rung residency: bytes "
                                            "of spilled prefix slabs"),
    "serving/prefix_spills_total": (COUNTER, "device->host spills at LRU "
                                             "eviction"),
    "serving/prefix_promotions_total": (COUNTER, "host->device promotes at "
                                                 "lookup (slab copy instead "
                                                 "of re-prefill)"),
    # ------------------------------------------------- paged KV pool (PR 13)
    "serving/pages_in_use": (GAUGE, "allocated KV pages per scheduler tick"),
    "serving/page_fragmentation": (GAUGE, "allocation-granularity waste: "
                                          "fraction of allocated page rows "
                                          "beyond slot reservations"),
    "serving/prefix_shared_pages": (GAUGE, "pages referenced more than once "
                                           "(zero-copy prefix sharing)"),
    "serving/cow_copies_total": (COUNTER, "copy-on-write boundary-page "
                                          "copies at prefix bind"),
    # ---------------------------------------------- speculative decoding (PR 18)
    "serving/spec_acceptance_rate": (GAUGE, "cumulative draft-token "
                                            "acceptance rate per verify round"),
    "serving/spec_proposed_total": (COUNTER, "draft tokens offered to the "
                                             "verifier"),
    "serving/spec_accepted_total": (COUNTER, "draft tokens accepted by the "
                                             "verify pass"),
    "serving/spec_draft_ms": (GAUGE, "proposer wall time of the last round"),
    # ------------------------------------------------------------------ router
    "router/queue_depth": (GAUGE, "router admission queue depth per tick"),
    "router/retried_total": (COUNTER, "checkpointless retries (re-enqueues)"),
    "router/evicted_total": (COUNTER, "request evictions (replica death/drain)"),
    "router/completed_total": (COUNTER, "routed requests finished"),
    "router/rejected_total": (COUNTER, "routed requests rejected"),
    "router/handed_off_total": (COUNTER, "requests handed off at drain"),
    "router/drain_ms": (GAUGE, "graceful-drain wall time"),
    "router/ttft_ms": (HISTOGRAM, "end-to-end TTFT across retry attempts"),
    "router/tpot_ms": (HISTOGRAM, "end-to-end TPOT across retry attempts"),
    "router/replica{i}/health": (GAUGE, "replica state code (0 live .. 4 retiring)"),
    "router/replica{i}/outstanding": (GAUGE, "running + queued at the replica"),
    "router/replica{i}/prefix_hit_rate": (GAUGE, "per-replica prefix hit rate"),
    # --------------------------------------- fleet KV economy (PR 19)
    "router/fleet_prefix_hit_rate": (GAUGE, "admission-level hit rate summed "
                                            "across all replicas (in-process "
                                            "counters + hosted heartbeat "
                                            "gossip)"),
    "router/prefix_routed_total": (COUNTER, "dispatches won on a non-zero "
                                            "expected-prefix-saved score"),
    "router/prefix_saved_tokens_total": (COUNTER, "cumulative predicted "
                                                  "prefill tokens saved by "
                                                  "prefix-aware dispatch"),
    # --------------------------------------------- elastic control plane (PR 12)
    "router/live_replicas": (GAUGE, "attached non-DEAD replicas per tick"),
    "router/target_replicas": (GAUGE, "autoscaler's desired replica count"),
    "router/shed_total": (COUNTER, "requests shed at admission (infeasible "
                                   "deadline under SLO-aware admission)"),
    "router/deferred_total": (COUNTER, "low-priority requests deferred under "
                                       "the degradation ladder"),
    "router/deadline_miss_total": (COUNTER, "post-admission deadline expiries"),
    "router/degradation_rung": (GAUGE, "degradation ladder rung (0 healthy, "
                                       "1 defer-low, 2 shed-infeasible, "
                                       "3 admission-closed)"),
    "autoscale/scale_up_total": (COUNTER, "replicas added by the autoscaler"),
    "autoscale/scale_down_total": (COUNTER, "replicas retired by the autoscaler"),
    "autoscale/replica_seconds": (COUNTER, "integrated attached-replica "
                                           "seconds (provisioned capacity)"),
    # --------------------------------------- hosted replica supervision (PR 15)
    "host/restarts_total": (COUNTER, "supervised child-process respawns "
                                     "across hosted replicas"),
    "host/backoff_s": (GAUGE, "longest pending respawn backoff (0 = none)"),
    "host/child_rss_bytes": (GAUGE, "max child RSS across hosted replicas"),
    "host/pipe_lag_ms": (GAUGE, "max heartbeat pipe transit+age across "
                                "hosted replicas"),
    # ------------------------------------------ socket replica transport (PR 16)
    "net/frames_total": (COUNTER, "wire frames moved (sent + decoded) per "
                                  "socket link"),
    "net/reconnects_total": (COUNTER, "successful redials by the reconnect "
                                      "state machine"),
    "net/quarantined_frames_total": (COUNTER, "frame-level quarantine "
                                              "events (bad magic/CRC/length "
                                              "-> resync)"),
    "net/partition_trips_total": (COUNTER, "connection severs observed "
                                           "(RST/FIN/partition aging out)"),
    "net/rtt_ms": (HISTOGRAM, "ping/pong round-trip per socket link"),
    # ---------------------------------------------------------------- training
    "Train/Samples/train_loss": (GAUGE, "loss at each optimizer step"),
    "Train/Samples/lr": (GAUGE, "learning rate at each optimizer step"),
    "Train/Samples/loss_scale": (GAUGE, "fp16 dynamic loss scale"),
    "Train/Comm/bytes_on_wire": (GAUGE, "modeled collective bytes per step "
                                        "(trace-time CollectiveSpans)"),
    "Train/Comm/overlap_ratio": (GAUGE, "fraction of wire bytes moved by "
                                        "overlap-scheduled collectives"),
    "Train/step_time_ms": (HISTOGRAM, "host wall time per optimizer step"),
    "Train/tokens_per_sec": (GAUGE, "global batch tokens / step time"),
    "Train/mfu": (GAUGE, "modeled model-flops utilization "
                         "(profiled flops / step time / peak)"),
    # ------------------------------------------ latency attribution (PR 14)
    "latency/e2e_ms": (HISTOGRAM, "end-to-end request latency (root span)"),
    "latency/phase/queue_ms": (HISTOGRAM, "admission-queue wait per request"),
    "latency/phase/admission_ms": (HISTOGRAM, "admission work (prefix "
                                              "lookup) per request"),
    "latency/phase/kv_restore_ms": (HISTOGRAM, "prefix-slab restore / page "
                                               "bind per request"),
    "latency/phase/prefill_ms": (HISTOGRAM, "prefill compute per request"),
    "latency/phase/decode_ms": (HISTOGRAM, "decode-chunk compute per request"),
    "latency/phase/gap_ms": (HISTOGRAM, "inter-chunk scheduling gap per "
                                        "request"),
    "latency/phase/retry_lost_ms": (HISTOGRAM, "time lost to abandoned lanes "
                                               "(evicted attempts) per "
                                               "request"),
    # ------------------------------------------- flight recorder (PR 14)
    "flight/retained_traces": (GAUGE, "span trees retained by tail sampling"),
    "flight/retained_spans": (GAUGE, "total spans across retained trees"),
    "flight/dumps_total": (COUNTER, "flight bundles written"),
    # ------------------------------------------- anomaly detector (PR 14)
    "anomaly/trips_total": (COUNTER, "anomaly-detector trips (rate-limited)"),
    "anomaly/last_score": (GAUGE, "robust-z score of the last trip"),
    # --------------------------------------------------------------- inference
    "inference/ttft_ms": (HISTOGRAM, "prefill latency per generate call"),
    "inference/tpot_ms": (HISTOGRAM, "decode seconds-per-token per generate"),
    "inference/decode_tokens_per_sec": (GAUGE, "batch-aggregate decode tok/s"),
    "inference/weight_quant/bits": (GAUGE, "quantized weight width"),
    "inference/weight_quant/matrices_quantized": (GAUGE, "matrices quantized"),
    "inference/weight_quant/matrices_kept_fp": (GAUGE, "matrices kept fp"),
    "inference/weight_quant/modeled_step_bytes": (GAUGE,
                                                  "modeled weight bytes/step"),
    "inference/weight_quant/reduction_vs_bf16": (GAUGE,
                                                 "modeled stream reduction"),
}

_TEMPLATE_SEG = re.compile(r"\{[A-Za-z_][A-Za-z0-9_]*\}")


def _pattern_regex(pattern: str) -> "re.Pattern":
    parts = _TEMPLATE_SEG.split(pattern)
    return re.compile(r"\d+".join(re.escape(p) for p in parts) + r"$")


_COMPILED: List[Tuple[str, "re.Pattern"]] = [
    (p, _pattern_regex(p)) for p in TAGS
]


def resolve(tag: str) -> Optional[str]:
    """The schema pattern a concrete tag matches, or None if undeclared.
    ``tag`` may itself be a wildcard form (``router/replica*/health``, the
    lint's rendering of an f-string) — a ``*`` segment matches ``{i}``."""
    if tag in TAGS:
        return tag
    if "*" in tag:
        want = re.escape(tag).replace(r"\*", r"\{[A-Za-z_][A-Za-z0-9_]*\}")
        rx = re.compile(want + "$")
        matches = [p for p in TAGS if rx.match(p)]
        return matches[0] if len(matches) == 1 else None
    for pattern, rx in _COMPILED:
        if rx.match(tag):
            return pattern
    return None


def kind_of(tag: str) -> str:
    """Instrument kind for a concrete tag. Raises ``KeyError`` on an
    undeclared tag — the runtime face of the lint."""
    pattern = resolve(tag)
    if pattern is None:
        raise KeyError(
            f"metric tag {tag!r} is not declared in observability.schema.TAGS "
            "— declare it (kind + help) before emitting it")
    return TAGS[pattern][0]


def is_declared(tag: str) -> bool:
    return resolve(tag) is not None


# --------------------------------------------------------------------- linting
#: modules whose emission sites the tag lint walks (repo-relative paths)
EMITTER_MODULES = (
    "deepspeed_tpu/inference/serving/telemetry.py",
    "deepspeed_tpu/inference/speculative.py",
    "deepspeed_tpu/inference/serving/router.py",
    "deepspeed_tpu/inference/serving/autoscale.py",
    "deepspeed_tpu/inference/serving/host.py",
    "deepspeed_tpu/inference/serving/net.py",
    "deepspeed_tpu/runtime/engine.py",
    "deepspeed_tpu/inference/engine.py",
    "deepspeed_tpu/observability/metrics.py",
    "deepspeed_tpu/observability/attribution.py",
    "deepspeed_tpu/observability/flight.py",
    "deepspeed_tpu/observability/anomaly.py",
)


def iter_emission_tags(path: str) -> Iterator[Tuple[str, int]]:
    """Yield ``(tag_literal, lineno)`` for every tag-shaped string that feeds
    a metric emission in ``path``. The walker itself lives in the shared AST
    lint framework (``analysis.ast_rules.iter_emission_tags``) — this module
    keeps the schema-facing API and the declaration table."""
    from ..analysis.ast_rules import iter_emission_tags as _iter
    yield from _iter(path)


def emission_tag_rule():
    """The schema lint as an :class:`~deepspeed_tpu.analysis.ast_rules.AstRule`
    — the form ``bin/ds-tpu-lint`` runs it in, next to the bare-assert and
    hot-path-sync rules."""
    from ..analysis.ast_rules import EmissionTagRule
    return EmissionTagRule(resolve, EMITTER_MODULES)


def lint_emission_sites(repo_root: str) -> List[str]:
    """Every undeclared tag across :data:`EMITTER_MODULES`, as
    ``"path:line: tag"`` strings (empty list = clean). Runs under the shared
    AST rule runner (one framework for every source-level rule)."""
    from ..analysis.ast_rules import run_ast_rules
    result = run_ast_rules(repo_root, [emission_tag_rule()],
                           paths=EMITTER_MODULES)
    # a syntax error in an emitter module surfaces as a runner finding with
    # no 'tag' detail — report it as a problem, don't crash on it
    return [f"{f.site}: {f.details.get('tag', f.message)}"
            for f in result.findings]
