"""Tail-latency flight recorder: bounded tail sampling + decision journal.

Aggregate histograms say *that* p99 blew up; they cannot say *why*, because by
the time the dashboard shows the spike the offending requests' evidence is
gone. The flight recorder closes that loop: it rides the tracer's span stream
(:meth:`~.trace.Tracer.add_sink`), attributes every completed request
(:mod:`.attribution` — phase histograms into the registry), and retains **full
span trees** for exactly the requests worth a post-mortem:

- **slow** — e2e latency above ``slow_p95_mult`` × an EWMA-smoothed p95 of
  recent e2e (adaptive: the bar follows the workload, so a uniformly slow
  soak doesn't retain everything and a fast one doesn't retain nothing);
- **failed / expired / shed / handed-off / cancelled-by-error** — any root
  state other than ``finished``;
- **retried / evicted** — the root records retries, or any lane in the tree
  closed ``state=abandoned``/``evicted`` (a killed replica's force-closed
  lane rides along with the retry that recovered it);
- a **1-in-N uniform sample** of healthy requests (the baseline to diff the
  anomalies against).

Everything else keeps only its attribution row (bounded). Retention is doubly
bounded — max retained traces AND max total retained spans — with drop-oldest
eviction, counted, never silent.

The recorder also keeps a structured **control-plane decision journal**: the
router's degradation-rung and replica-health transitions, admission sheds,
autoscale decisions, and anomaly trips append ``{"t", "kind", ...}`` entries
through the module-level :func:`journal` hook (one global load + None check
when no recorder is installed — hot-path safe). A :meth:`FlightRecorder.dump`
bundle is a **Perfetto-loadable** Chrome trace of the retained trees whose
``otherData`` carries the journal, rolling registry snapshots, recent anomaly
trips, and the p50-vs-p99 phase breakdown — triggered on demand, by
``SIGUSR1`` (``SIGUSR2`` stays the PR 10 XLA profiler), at router drain, and
by the anomaly detector.
"""

import json
import os
import signal
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional

from . import attribution
from .metrics import record_events
from .trace import chrome_events_from
from ..utils.logging import logger


@dataclass
class FlightConfig:
    slow_p95_mult: float = 3.0        # slow = e2e > mult * EWMA p95
    warmup_requests: int = 20         # no slow-retention before this many rows
    sample_every: int = 50            # uniform 1-in-N healthy sample
    p95_window: int = 256             # recent e2e window the p95 reads
    p95_alpha: float = 0.2            # EWMA smoothing of the windowed p95
    p95_refresh: int = 16             # completions between p95 recomputes
    #   (a per-completion percentile over the window is pure overhead — the
    #   EWMA bar moves slowly by design)
    max_open_traces: int = 512        # in-flight trace buffers (drop-oldest)
    max_spans_per_trace: int = 2048
    max_retained_traces: int = 64     # full-tree retention budget ...
    max_retained_spans: int = 20000   # ... and the global span budget
    rows: int = 4096                  # attribution rows kept (bounded)
    journal_len: int = 512
    snapshots: int = 16               # rolling registry snapshots in the dump
    snapshot_every_s: float = 2.0


class FlightRecorder:
    """Span-sink tail sampler over a :class:`~.trace.Tracer`.

    ``dump_path`` is the default bundle destination; automatic dumps (SIGUSR1,
    drain, anomaly trips) write numbered siblings next to it. ``dump_path=
    None`` disables automatic dumps (attribution/retention still run) —
    the overhead A/B uses that mode."""

    def __init__(self, config: Optional[FlightConfig] = None,
                 dump_path: Optional[str] = None, registry=None,
                 monitor=None):
        self.config = config or FlightConfig()
        self.dump_path = dump_path
        self._registry = registry
        # optional MonitorMaster-shaped backend: attribution events mirror
        # into it (loadgen --jsonl-metrics gains per-request phase rows)
        # WITHOUT attaching the monitor to the registry, which would
        # double-write every telemetry tag (telemetry already feeds both)
        self.monitor = monitor
        self._lock = threading.Lock()
        self._open: "OrderedDict[str, List[Dict]]" = OrderedDict()
        self.rows: deque = deque(maxlen=self.config.rows)
        self.retained: deque = deque()
        self.retained_spans = 0
        self.retained_evicted = 0
        self.open_dropped = 0         # in-flight trace buffers evicted
        self.span_drops = 0           # spans over the per-trace bound
        self.completions = 0
        self.dumps = 0
        self._journal: deque = deque(maxlen=self.config.journal_len)
        self._snapshots: deque = deque(maxlen=self.config.snapshots)
        self._last_snapshot = 0.0
        self._e2e_window: deque = deque(maxlen=self.config.p95_window)
        self._p95_ewma: Optional[float] = None
        self._since_p95 = 0
        self._dump_requested = False
        self._tracer = None
        self._prev_usr1 = None

    # ----------------------------------------------------------------- attach
    def attach(self, tracer) -> "FlightRecorder":
        """Sink onto ``tracer`` and install as THE process recorder (the
        module-level :func:`journal` hook routes here)."""
        self._tracer = tracer
        tracer.add_sink(self.on_span)
        install_recorder(self)
        return self

    def detach(self) -> None:
        if self._tracer is not None:
            self._tracer.remove_sink(self.on_span)
            self._tracer = None
        if get_recorder() is self:
            install_recorder(None)

    def install_sigusr1(self):
        """Route ``SIGUSR1`` to :meth:`request_dump` (flag only — the next
        span commit performs the dump; a serving loop commits spans
        constantly). Returns the previous handler."""
        def _handler(signum, frame):
            self.request_dump()
        self._prev_usr1 = signal.signal(signal.SIGUSR1, _handler)
        return self._prev_usr1

    def request_dump(self) -> None:
        """Signal-handler safe: flag only."""
        self._dump_requested = True

    # ------------------------------------------------------------------- sink
    def on_span(self, span: Dict) -> None:
        """Tracer sink: buffer by trace id; a parentless span completes its
        trace (request roots commit last — the scheduler/router end them at
        finalize)."""
        tid = span.get("trace_id")
        if tid is None:
            return
        done = None
        with self._lock:
            buf = self._open.get(tid)
            if buf is None:
                while len(self._open) >= self.config.max_open_traces:
                    self._open.popitem(last=False)
                    self.open_dropped += 1
                buf = self._open[tid] = []
            if len(buf) < self.config.max_spans_per_trace:
                buf.append(span)
            else:
                self.span_drops += 1
            if not span.get("parent_id"):
                done = self._open.pop(tid, None)
        if done is not None and span.get("name") in attribution.ROOT_NAMES:
            self._finalize_trace(tid, done)
        self._housekeeping()

    def _housekeeping(self) -> None:
        if self._dump_requested:
            self._dump_requested = False
            self.dump_auto("sigusr1")
        if self.dump_path is None:
            return          # snapshots exist only to ride dump bundles
        now = time.monotonic()
        if now - self._last_snapshot >= self.config.snapshot_every_s:
            self._last_snapshot = now
            self._snapshots.append({"t": time.time(),
                                    "metrics": self._reg().snapshot()})

    def _reg(self):
        if self._registry is None:
            from .metrics import get_registry
            self._registry = get_registry()
        return self._registry

    # ------------------------------------------------------------- attribution
    def _finalize_trace(self, tid: str, spans: List[Dict]) -> None:
        row = attribution.attribute(spans)
        if row is None:
            return
        cfg = self.config
        with self._lock:
            self.completions += 1
            idx = self.completions
            self.rows.append(row)
            slow_bar = (cfg.slow_p95_mult * self._p95_ewma
                        if self._p95_ewma is not None
                        and len(self._e2e_window) >= min(cfg.warmup_requests,
                                                         cfg.p95_window)
                        else None)
            reason = self._keep_reason(row, spans, slow_bar, idx)
            # the bar updates AFTER the decision: a request is judged against
            # the distribution that existed when it ran. Recomputing the
            # window percentile is amortized over p95_refresh completions —
            # the EWMA bar moves slowly by design, and a per-completion
            # percentile was the recorder's single biggest hot-path cost.
            # Only FINISHED requests define the family: instant shed roots
            # (e2e≈0) and expired/failed tails would drag the windowed p95
            # toward 0 during an incident, collapsing the slow bar and
            # mass-retaining healthy traffic as "slow".
            state = row.get("state")
            if row["e2e_ms"] > 0.0 and (state is None or state == "finished"):
                self._e2e_window.append(row["e2e_ms"])
                self._since_p95 += 1
            if self._e2e_window \
                    and (self._p95_ewma is None
                         or self._since_p95 >= cfg.p95_refresh):
                self._since_p95 = 0
                xs = sorted(self._e2e_window)
                p95_now = xs[int(0.95 * (len(xs) - 1))]
                a = cfg.p95_alpha
                self._p95_ewma = (p95_now if self._p95_ewma is None
                                  else (1 - a) * self._p95_ewma + a * p95_now)
            if reason is not None:
                self.retained.append({"trace_id": tid, "reason": reason,
                                      "t": time.time(), "spans": spans,
                                      "attribution": row})
                self.retained_spans += len(spans)
                while (len(self.retained) > cfg.max_retained_traces
                       or self.retained_spans > cfg.max_retained_spans):
                    gone = self.retained.popleft()
                    self.retained_spans -= len(gone["spans"])
                    self.retained_evicted += 1
            n_traces, n_spans = len(self.retained), self.retained_spans
        # only phases that HAPPENED are observed: zero rows would flood every
        # histogram's underflow bucket and double the per-completion emission
        # cost; "queue time when there was queueing" is the useful quantile
        # (instant shed roots contribute no latency observation at all)
        events = ([(attribution.E2E_TAG, row["e2e_ms"], idx)]
                  if row["e2e_ms"] > 0.0 else [])
        for phase, ms in row["phases"].items():
            if ms > 0.0:
                events.append((attribution.PHASE_TAGS[phase], ms, idx))
        events.append(("flight/retained_traces", float(n_traces), idx))
        events.append(("flight/retained_spans", float(n_spans), idx))
        record_events(events)
        if self.monitor is not None and getattr(self.monitor, "enabled",
                                                False):
            self.monitor.write_events(events)

    def _keep_reason(self, row: Dict, spans: List[Dict],
                     slow_bar: Optional[float], idx: int) -> Optional[str]:
        state = row.get("state")
        if state is not None and state != "finished":
            return state                      # failed/expired/shed/handed_off
        if (row.get("retried") or 0) > 0 or (row.get("attempts") or 1) > 1:
            return "retried"
        if row.get("failed_lanes"):       # attribution already walked the
            return "evicted"              # tree — no second span scan here
        if slow_bar is not None and row["e2e_ms"] > slow_bar:
            return "slow"
        if self.config.sample_every and idx % self.config.sample_every == 0:
            return "sample"
        return None

    # ---------------------------------------------------------------- journal
    def journal(self, kind: str, attrs: Optional[Dict] = None) -> None:
        entry = {"t": time.time(), "kind": str(kind)}
        if attrs:
            entry.update(attrs)
        self._journal.append(entry)

    def journal_entries(self) -> List[Dict]:
        return list(self._journal)

    # ------------------------------------------------------------------- dump
    def breakdown(self) -> Dict:
        """The p50-vs-p99 phase-share breakdown over the attribution rows."""
        with self._lock:
            rows = list(self.rows)
        return attribution.phase_breakdown(rows)

    def stats(self) -> Dict:
        """Status-plane summary (``/statusz``)."""
        with self._lock:
            reasons: Dict[str, int] = {}
            for r in self.retained:
                reasons[r["reason"]] = reasons.get(r["reason"], 0) + 1
            return {"completions": self.completions,
                    "rows": len(self.rows),
                    "retained_traces": len(self.retained),
                    "retained_spans": self.retained_spans,
                    "retained_evicted": self.retained_evicted,
                    "retained_reasons": reasons,
                    "open_traces": len(self._open),
                    "open_dropped": self.open_dropped,
                    "span_drops": self.span_drops,
                    "dumps": self.dumps,
                    "slow_bar_ms": (self.config.slow_p95_mult * self._p95_ewma
                                    if self._p95_ewma is not None else None)}

    def dump(self, path: Optional[str] = None, reason: str = "manual",
             anomalies: Optional[List[Dict]] = None) -> Optional[str]:
        """Write the Perfetto-loadable bundle: retained span trees as Chrome
        trace events, with the journal / rolling metrics snapshots / anomaly
        trips / phase breakdown under ``otherData``. Returns the path (None
        when no destination is configured)."""
        path = path or self.dump_path
        if path is None:
            return None
        with self._lock:
            retained = list(self.retained)
            journal_ = list(self._journal)
            snapshots = list(self._snapshots)
            stats = {"retained_evicted": self.retained_evicted,
                     "open_dropped": self.open_dropped,
                     "span_drops": self.span_drops,
                     "completions": self.completions}
        spans: List[Dict] = []
        for r in retained:
            spans.extend(r["spans"])
        bundle = {
            "traceEvents": chrome_events_from(spans),
            "displayTimeUnit": "ms",
            "otherData": {
                "kind": "flight_bundle",
                "reason": reason,
                "t": time.time(),
                "retained": [{"trace_id": r["trace_id"],
                              "reason": r["reason"], "t": r["t"],
                              "spans": len(r["spans"]),
                              "attribution": r["attribution"]}
                             for r in retained],
                "breakdown": attribution.phase_breakdown(
                    [r["attribution"] for r in retained] or list(self.rows)),
                "journal": journal_,
                "metrics_snapshots": snapshots
                + [{"t": time.time(), "metrics": self._reg().snapshot()}],
                "anomalies": anomalies if anomalies is not None
                else _recent_anomalies(),
                "drops": stats,
            },
        }
        with open(path, "w") as f:
            json.dump(bundle, f)
        self.dumps += 1
        record_events([("flight/dumps_total", float(self.dumps), self.dumps)])
        logger.info(f"[flight] bundle ({reason}) -> {path}: "
                    f"{len(retained)} trace(s), {len(spans)} span(s)")
        return path

    def dump_auto(self, reason: str,
                  anomalies: Optional[List[Dict]] = None) -> Optional[str]:
        """Numbered sibling of ``dump_path`` for automatic triggers (SIGUSR1,
        drain, anomaly) — the final/explicit bundle is never clobbered."""
        if self.dump_path is None:
            return None
        stem, ext = os.path.splitext(self.dump_path)
        return self.dump(f"{stem}.auto{self.dumps}{ext or '.json'}",
                         reason=reason, anomalies=anomalies)


# ------------------------------------------------------- process-wide recorder
_recorder: Optional[FlightRecorder] = None


def install_recorder(rec: Optional[FlightRecorder]) -> None:
    global _recorder
    _recorder = rec


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


def journal(kind: str, **attrs) -> None:
    """Hot-path decision-journal hook: one global load + None check when no
    recorder is installed. Control-plane sites (router rung/health
    transitions, sheds, autoscale decisions, anomaly trips) call this."""
    r = _recorder
    if r is not None:
        r.journal(kind, attrs)


def drain_dump() -> Optional[str]:
    """Router drain epilogue: dump the bundle if a recorder is installed."""
    r = _recorder
    if r is not None:
        return r.dump_auto("router_drain")
    return None


def _recent_anomalies() -> List[Dict]:
    """Recent trips from the installed anomaly detector (if any) — lazy
    import; anomaly.py imports this module, not vice versa."""
    from .anomaly import get_detector
    det = get_detector()
    return list(det.recent) if det is not None else []
