"""On-demand XLA device-profiler capture.

Host spans (``observability.trace``) say where wall time went; only the XLA
profiler says what the device executed during a decode chunk or train step.
This module makes that capture operational instead of a notebook trick:

- **programmatic**: :class:`ProfilerCapture` wraps
  ``jax.profiler.start_trace`` / ``stop_trace`` so a capture covers exactly N
  *ticks* (train steps or decode chunks — the instrumented hot paths call
  :func:`tick` once per unit of work);
- **on-demand**: arm at construction (``capture_on_start``) or at runtime via
  ``SIGUSR2`` (:meth:`install_sigusr2`) — send the signal to a live
  ``deepspeed-serve``/trainer and the *next* N ticks are captured to the
  logdir, then the profiler stops. No restart, no steady-state overhead;
- **aligned**: the ``TraceAnnotation`` scopes wired at prefill / decode-chunk
  / collective call sites (``utils/nvtx.py``) land inside the capture, so the
  device timeline lines up with the host spans by name.

The module-level :func:`tick` costs one global load + ``is None`` check when
no capture is configured — hot-path safe.
"""

import os
import signal
import threading
from typing import Optional

from ..utils.logging import logger


class ProfilerCapture:
    """Capture the next ``num_ticks`` units of work when armed."""

    def __init__(self, logdir: str, num_ticks: int = 4,
                 capture_on_start: bool = False):
        if num_ticks < 1:
            raise ValueError(f"num_ticks must be >= 1, got {num_ticks}")
        self.logdir = str(logdir)
        self.num_ticks = int(num_ticks)
        self._armed = bool(capture_on_start)
        self._remaining = 0
        self._active = False
        self._lock = threading.Lock()
        self.captures = 0            # completed captures this process

    # ------------------------------------------------------------------ state
    @property
    def armed(self) -> bool:
        return self._armed

    @property
    def active(self) -> bool:
        return self._active

    def arm(self, num_ticks: Optional[int] = None) -> None:
        """Signal-handler safe: flag only; the next tick starts the trace."""
        if num_ticks is not None:
            self.num_ticks = int(num_ticks)
        self._armed = True

    def install_sigusr2(self):
        """Route ``SIGUSR2`` to :meth:`arm`; returns the previous handler."""
        def _handler(signum, frame):
            self.arm()
        return signal.signal(signal.SIGUSR2, _handler)

    # ------------------------------------------------------------------- ticks
    def tick(self, kind: str = "step") -> None:
        """One unit of work completed (train step / decode chunk). Starts the
        device trace when armed, stops it after ``num_ticks``."""
        if not self._armed and not self._active:
            return
        with self._lock:
            if self._armed and not self._active:
                self._armed = False
                os.makedirs(self.logdir, exist_ok=True)
                import jax
                try:
                    jax.profiler.start_trace(self.logdir)
                except Exception as e:            # a capture must never kill
                    logger.warning(f"profiler capture failed to start: {e}")
                    return
                self._active = True
                self._remaining = self.num_ticks
                logger.info(f"[obs] XLA profiler capture started "
                            f"({self.num_ticks} {kind}(s) -> {self.logdir})")
                return
            if self._active:
                self._remaining -= 1
                if self._remaining <= 0:
                    self._finish()

    def _finish(self) -> None:
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception as e:                    # pragma: no cover
            logger.warning(f"profiler capture failed to stop: {e}")
        self._active = False
        self.captures += 1
        logger.info(f"[obs] XLA profiler capture written to {self.logdir}")

    def close(self) -> None:
        """Stop a capture left running (e.g. the loop ended mid-capture)."""
        with self._lock:
            if self._active:
                self._finish()


_capture: Optional[ProfilerCapture] = None


def configure_capture(logdir: Optional[str], num_ticks: int = 4,
                      capture_on_start: bool = False,
                      sigusr2: bool = True) -> Optional[ProfilerCapture]:
    """Install the process-wide capture (``logdir=None`` uninstalls)."""
    global _capture
    if _capture is not None:
        _capture.close()
    if logdir is None:
        _capture = None
        return None
    _capture = ProfilerCapture(logdir, num_ticks=num_ticks,
                               capture_on_start=capture_on_start)
    if sigusr2:
        try:
            _capture.install_sigusr2()
        except ValueError:        # not the main thread: arm() still works
            logger.warning("SIGUSR2 trigger unavailable off the main thread; "
                           "use ProfilerCapture.arm()")
    return _capture


def get_capture() -> Optional[ProfilerCapture]:
    return _capture


def tick(kind: str = "step") -> None:
    """Hot-path hook: one global load + None check when no capture exists."""
    c = _capture
    if c is not None:
        c.tick(kind)
