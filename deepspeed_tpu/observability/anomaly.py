"""Anomaly-triggered diagnostics: EWMA+MAD scoring over registry streams.

Out-of-family behavior should capture its own evidence: by the time a human
reads the dashboard, the stalled chunk and the queue spike that caused the
page are long gone. The detector watches a small set of registry streams
(TTFT / TPOT / queue depth / page fragmentation / retry rate), scores each
observation with a robust z — ``|x - EWMA| / (1.4826 * MAD_EWMA + floor)``,
where the MAD term is an EWMA of absolute deviations (median-free so it stays
O(1)) — and, when a score clears ``threshold`` after warm-up, **trips**:

- the trip (signal name, value, EWMA, MAD, score, threshold) is recorded and
  journaled into the flight recorder's decision journal;
- the flight recorder dumps a bundle (so the anomalous window's retained span
  trees, metrics snapshots, and coincident control-plane decisions land in
  one Perfetto-loadable file);
- the PR 10 XLA profiler capture is **armed for the next K ticks** (if one is
  configured) — the out-of-family decode chunks self-capture their device
  profile, no human in the loop.

Trips are rate-limited (``cooldown_s``): a sustained incident produces one
bundle, not a bundle per request. Counter-kind streams (``*_total``) are
scored on their per-event **delta** — a retry *rate* spike trips, a large
cumulative total does not.

The detector implements the registry's monitor interface (``enabled`` +
``write_events``), so ``registry.attach_monitor(detector)`` taps every
emission without touching the emitters.
"""

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from collections import deque

from . import schema
from .metrics import record_events
from ..utils.logging import logger

#: default watched streams: the issue-level tail signals. Histogram tags are
#: per-event observations; gauge tags are per-tick samples; counter tags are
#: scored on deltas (rates).
DEFAULT_WATCH = (
    "serving/ttft_ms", "serving/tpot_ms",
    "router/ttft_ms", "router/tpot_ms",
    "serving/queue_depth", "router/queue_depth",
    "serving/page_fragmentation",
    "router/retried_total",
)


@dataclass
class AnomalyConfig:
    threshold: float = 8.0        # robust-z trip bar
    alpha: float = 0.05           # EWMA weight (mean and MAD)
    min_obs: int = 16             # per-signal warm-up before scoring
    cooldown_s: float = 5.0       # global trip rate limit
    arm_profiler_ticks: int = 8   # XLA capture length on trip
    # robust-z floor: MAD of a quiet signal (queue depth pinned at 0) is ~0,
    # and a bare 1/MAD would trip on the first nonzero sample; the floor is
    # relative to the signal's own scale plus a small absolute term
    rel_floor: float = 0.05
    abs_floor: float = 1e-3
    watch: Tuple[str, ...] = DEFAULT_WATCH


@dataclass
class _SignalState:
    ewma: Optional[float] = None
    mad: float = 0.0
    n: int = 0
    last_total: Optional[float] = None   # counter kinds: delta base


class AnomalyDetector:
    """Attach with ``get_registry().attach_monitor(detector)``."""

    enabled = True                # monitor-interface gate the registry checks

    def __init__(self, config: Optional[AnomalyConfig] = None,
                 recorder=None):
        self.config = config or AnomalyConfig()
        self.recorder = recorder
        self._watch = set(self.config.watch)
        self._state: Dict[str, _SignalState] = {}
        self._counter_kind: Dict[str, bool] = {}
        self.trips = 0
        self.suppressed = 0           # would-trip events inside the cooldown
        self.recent: deque = deque(maxlen=64)
        self._last_trip: Optional[float] = None

    # ---------------------------------------------------------------- monitor
    def write_events(self, events) -> None:
        for tag, value, step in events:
            if tag in self._watch:
                self.observe(tag, float(value))

    # ---------------------------------------------------------------- scoring
    def _is_counter(self, tag: str) -> bool:
        kind = self._counter_kind.get(tag)
        if kind is None:
            kind = schema.kind_of(tag) == schema.COUNTER
            self._counter_kind[tag] = kind
        return kind

    def observe(self, tag: str, value: float,
                now: Optional[float] = None) -> Optional[Dict]:
        """Score one observation; returns the trip record when it trips."""
        cfg = self.config
        st = self._state.get(tag)
        if st is None:
            st = self._state[tag] = _SignalState()
        if self._is_counter(tag):
            if st.last_total is None:
                st.last_total = value
                return None
            value, st.last_total = max(0.0, value - st.last_total), value
        trip = None
        if st.ewma is not None and st.n >= cfg.min_obs:
            dev = abs(value - st.ewma)
            denom = (1.4826 * st.mad + cfg.rel_floor * abs(st.ewma)
                     + cfg.abs_floor)
            score = dev / denom
            if score > cfg.threshold:
                trip = self._trip(tag, value, st, score, now)
        # update AFTER scoring: the sample is judged against the family it
        # arrived into, and a huge outlier must not normalize itself
        a = cfg.alpha
        if st.ewma is None:
            st.ewma = value
        else:
            st.mad = (1 - a) * st.mad + a * abs(value - st.ewma)
            st.ewma = (1 - a) * st.ewma + a * value
        st.n += 1
        return trip

    def _trip(self, tag: str, value: float, st: _SignalState, score: float,
              now: Optional[float]) -> Optional[Dict]:
        cfg = self.config
        now = time.monotonic() if now is None else now
        if self._last_trip is not None \
                and now - self._last_trip < cfg.cooldown_s:
            self.suppressed += 1
            return None
        self._last_trip = now
        self.trips += 1
        record = {"t": time.time(), "signal": tag, "value": value,
                  "ewma": st.ewma, "mad": st.mad, "score": score,
                  "threshold": cfg.threshold}
        self.recent.append(record)
        logger.warning(f"[anomaly] {tag} out of family: value={value:.4g} "
                       f"ewma={st.ewma:.4g} score={score:.1f} "
                       f"(threshold {cfg.threshold})")
        rec = self.recorder
        if rec is None:
            from .flight import get_recorder
            rec = get_recorder()
        if rec is not None:
            rec.journal("anomaly", dict(record))
            # the trip carries its own evidence list: the dump must name the
            # triggering signal even when this detector isn't the installed one
            rec.dump_auto(f"anomaly:{tag}", anomalies=list(self.recent))
        # arm the PR 10 device-profiler capture: the next K decode chunks /
        # prefills / train steps self-capture their XLA timeline
        from .profiler import get_capture
        cap = get_capture()
        if cap is not None:
            cap.arm(cfg.arm_profiler_ticks)
        record_events([("anomaly/trips_total", float(self.trips), self.trips),
                       ("anomaly/last_score", float(score), self.trips)])
        return record

    def snapshot(self) -> Dict:
        return {"trips": self.trips, "suppressed": self.suppressed,
                "recent": list(self.recent),
                "signals": {tag: {"ewma": st.ewma, "mad": st.mad, "n": st.n}
                            for tag, st in self._state.items()}}


# ------------------------------------------------------- process-wide detector
_detector: Optional[AnomalyDetector] = None


def install_detector(det: Optional[AnomalyDetector]) -> None:
    global _detector
    _detector = det


def get_detector() -> Optional[AnomalyDetector]:
    return _detector
