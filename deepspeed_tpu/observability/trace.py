"""Hierarchical wall-clock span tracer with request-/step-scoped context.

The host-side half of the observability spine: answers "where did this one
request's 1.9 s go?" by recording every stage of the serving column
(admit → queue wait → prefix-cache lookup → restore → prefill → decode chunks
→ retire, with router retry attempts as linked spans carrying the retry
replica id) and the training step (``train_step`` / ``grad_sync`` /
``checkpoint_commit``) as spans that share one **trace id per request/step**.

Design constraints, in order:

1. **Disabled is near-zero cost.** The tracer is a process-global that starts
   disabled; every instrumentation site costs one method call that returns
   immediately (``begin``/``start_span`` return ``None``, ``span()`` yields a
   shared null context). No allocation, no clock read.
2. **Bounded.** Finished spans land in a drop-oldest ring (``max_spans``);
   drops are counted, never silent.
3. **Cross-process joinable.** A ``SpanContext`` is two strings
   (``trace_id``, ``span_id``) that serialize over the ``serving/subproc.py``
   JSONL pipe; the child's spans carry the parent's trace id and
   :meth:`Tracer.ingest` merges them into the parent's buffer under the
   child's pid lane. Timestamps are wall-clock micros (``time.time``-anchored,
   advanced by the monotonic clock) so lanes from different processes line up.

Exports: Chrome-trace-event JSON (``{"traceEvents": [...]}``; load in
Perfetto / ``chrome://tracing``) and a JSONL stream (one finished span per
line) for tailing.
"""

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# span categories (Chrome "cat" field) — one per subsystem lane
CAT_SERVING = "serving"
CAT_ROUTER = "router"
CAT_TRAIN = "train"
CAT_AUTOSCALE = "autoscale"


class SpanContext:
    """The cross-boundary identity of a span: what you put on a wire."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def to_dict(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @staticmethod
    def from_dict(d) -> Optional["SpanContext"]:
        if not d or not d.get("trace_id"):
            return None
        return SpanContext(str(d["trace_id"]), str(d.get("span_id", "")))


class OpenSpan:
    """A started-but-unfinished span (kept on the owning handle/engine)."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id", "t0",
                 "attrs", "tid")

    def __init__(self, name, cat, trace_id, span_id, parent_id, t0, attrs,
                 tid):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = t0
        self.attrs = attrs or {}
        self.tid = tid

    @property
    def ctx(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)


class _NullCtx:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _SpanCtx:
    __slots__ = ("_tracer", "_open")

    def __init__(self, tracer, open_span):
        self._tracer = tracer
        self._open = open_span

    def __enter__(self):
        return self._open

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self._open.attrs["error"] = exc_type.__name__
        self._tracer.end_span(self._open)
        return False


def _parent_of(parent) -> tuple:
    """(trace_id, span_id) from an OpenSpan / SpanContext / None."""
    if parent is None:
        return None, None
    return parent.trace_id, getattr(parent, "span_id", None)


class Tracer:
    """Process-wide span recorder. ``enable()`` before the run; instrument
    sites call through unconditionally and pay ~nothing while disabled."""

    def __init__(self, max_spans: int = 200_000):
        self.enabled = False
        self.max_spans = int(max_spans)
        self._spans: "deque[Dict]" = deque(maxlen=self.max_spans)
        self.dropped = 0
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._pid_label = f"pid{os.getpid()}"
        self._stream = None
        # span sinks: callables fed every FINISHED span (the flight recorder's
        # attachment point). Empty-list check per commit — near-zero when none.
        self._sinks: List = []
        # wall-anchored monotonic clock: cross-process lanes align on wall
        # time, in-process durations stay monotonic
        self._mono0 = time.monotonic()
        self._wall0 = time.time()

    # ------------------------------------------------------------------ admin
    def enable(self, pid_label: Optional[str] = None,
               max_spans: Optional[int] = None) -> "Tracer":
        if max_spans is not None and max_spans != self.max_spans:
            self.max_spans = int(max_spans)
            with self._lock:
                self._spans = deque(self._spans, maxlen=self.max_spans)
        if pid_label:
            self._pid_label = pid_label
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def stream_to(self, path: str) -> None:
        """Also append every finished span to ``path`` as one JSON line."""
        self._stream = open(path, "a", buffering=1)

    def close_stream(self) -> None:
        if self._stream is not None:
            self._stream.close()
            self._stream = None

    # ------------------------------------------------------------------ sinks
    def add_sink(self, fn) -> None:
        """Register a callable fed every finished span dict (commit order,
        ingested spans included). The flight recorder attaches here; a sink
        must be fast and must never raise."""
        if fn not in self._sinks:
            self._sinks.append(fn)

    def remove_sink(self, fn) -> None:
        if fn in self._sinks:
            self._sinks.remove(fn)

    # ------------------------------------------------------------------ clock
    def ts_us(self, mono: Optional[float] = None) -> float:
        """Wall-anchored timestamp in µs from a ``time.monotonic`` reading."""
        m = time.monotonic() if mono is None else mono
        return (self._wall0 + (m - self._mono0)) * 1e6

    # ------------------------------------------------------------------- spans
    def _new_id(self) -> str:
        return f"{os.getpid():x}.{next(self._ids):x}"

    def new_trace_id(self) -> str:
        return f"t{os.getpid():x}.{next(self._ids):x}.{os.urandom(3).hex()}"

    def begin(self, name: str, cat: str = CAT_SERVING,
              ctx: Optional[SpanContext] = None, attrs: Optional[Dict] = None,
              t0: Optional[float] = None, tid: Optional[str] = None
              ) -> Optional[OpenSpan]:
        """Open a ROOT-scoped span. With ``ctx`` (a propagated parent), the new
        span joins that trace under that parent; otherwise a fresh trace id is
        minted — this is the request/step scope boundary."""
        if not self.enabled:
            return None
        trace_id, parent_id = _parent_of(ctx)
        if trace_id is None:
            trace_id = self.new_trace_id()
        return OpenSpan(name, cat, trace_id, self._new_id(), parent_id,
                        time.monotonic() if t0 is None else t0, attrs,
                        tid or threading.current_thread().name)

    def start_span(self, name: str, parent=None, cat: Optional[str] = None,
                   attrs: Optional[Dict] = None, t0: Optional[float] = None,
                   tid: Optional[str] = None) -> Optional[OpenSpan]:
        """Open a child span under ``parent`` (OpenSpan or SpanContext)."""
        if not self.enabled or parent is None:
            return None
        trace_id, parent_id = _parent_of(parent)
        return OpenSpan(name, cat or getattr(parent, "cat", CAT_SERVING),
                        trace_id, self._new_id(), parent_id,
                        time.monotonic() if t0 is None else t0, attrs,
                        tid or threading.current_thread().name)

    def end_span(self, open_span: Optional[OpenSpan],
                 t1: Optional[float] = None,
                 attrs: Optional[Dict] = None) -> None:
        if open_span is None:
            return
        if attrs:
            open_span.attrs.update(attrs)
        t1 = time.monotonic() if t1 is None else t1
        self._commit(open_span.name, open_span.cat, open_span.trace_id,
                     open_span.span_id, open_span.parent_id,
                     self.ts_us(open_span.t0),
                     max((t1 - open_span.t0) * 1e6, 0.0),
                     open_span.attrs, open_span.tid)

    def span(self, name: str, parent=None, cat: str = CAT_SERVING,
             attrs: Optional[Dict] = None):
        """Context manager. With ``parent`` the span nests under it; without,
        it roots a fresh (step-scoped) trace id."""
        if not self.enabled:
            return _NULL
        if parent is not None:
            return _SpanCtx(self, self.start_span(name, parent, cat, attrs))
        return _SpanCtx(self, self.begin(name, cat, None, attrs))

    def record_span(self, name: str, parent, t0: float, t1: float,
                    cat: Optional[str] = None, attrs: Optional[Dict] = None,
                    tid: Optional[str] = None) -> None:
        """Retroactive span between two ``time.monotonic`` readings (e.g.
        queue wait, measured arrival→admit)."""
        if not self.enabled or parent is None:
            return
        trace_id, parent_id = _parent_of(parent)
        self._commit(name, cat or getattr(parent, "cat", CAT_SERVING),
                     trace_id, self._new_id(), parent_id, self.ts_us(t0),
                     max((t1 - t0) * 1e6, 0.0), attrs or {},
                     tid or threading.current_thread().name)

    def instant(self, name: str, parent, cat: Optional[str] = None,
                attrs: Optional[Dict] = None) -> None:
        if not self.enabled or parent is None:
            return
        now = time.monotonic()
        self.record_span(name, parent, now, now, cat=cat, attrs=attrs)

    def _commit(self, name, cat, trace_id, span_id, parent_id, ts, dur,
                attrs, tid) -> None:
        span = {"name": name, "cat": cat, "trace_id": trace_id,
                "span_id": span_id, "parent_id": parent_id, "ts": ts,
                "dur": dur, "pid": self._pid_label, "tid": tid,
                "attrs": attrs}
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(span)
        if self._stream is not None:
            self._stream.write(json.dumps(span) + "\n")
        if self._sinks:
            for fn in self._sinks:
                fn(span)

    # ----------------------------------------------------------- cross-process
    def ingest(self, spans: List[Dict], pid_label: Optional[str] = None
               ) -> None:
        """Merge spans exported by another process (its ``drain()`` output).
        Works even while this tracer is disabled — the parent may collect a
        child's spans without tracing itself."""
        ingested = []
        with self._lock:
            for s in spans:
                s = dict(s)
                if pid_label:
                    s["pid"] = pid_label
                if len(self._spans) == self._spans.maxlen:
                    self.dropped += 1
                self._spans.append(s)
                ingested.append(s)
        if self._sinks:
            for s in ingested:
                for fn in self._sinks:
                    fn(s)

    def drain(self) -> List[Dict]:
        """Remove and return every finished span (the subprocess streaming
        path: the child drains after each scheduler step and ships the batch
        over its stdout pipe)."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
        return out

    @property
    def spans(self) -> List[Dict]:
        with self._lock:
            return list(self._spans)

    # ---------------------------------------------------------------- exports
    def chrome_events(self) -> List[Dict]:
        """Chrome trace events ('X' completes + 'M' lane metadata)."""
        return chrome_events_from(self.spans)

    def export_chrome(self, path: str) -> int:
        """Write Perfetto-loadable Chrome-trace JSON; returns the span count."""
        events = self.chrome_events()
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms",
                       "otherData": {"dropped_spans": self.dropped}}, f)
        return sum(1 for e in events if e["ph"] == "X")


def chrome_events_from(spans: List[Dict]) -> List[Dict]:
    """Chrome trace events ('X' completes + 'M' lane metadata) from finished
    span dicts. Shared by :meth:`Tracer.chrome_events` and the flight
    recorder's dump bundle (which exports RETAINED trees, not the whole ring),
    so both artifacts stay Perfetto-loadable through one builder."""
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    events: List[Dict] = []
    for s in spans:
        pid = pids.setdefault(s["pid"], len(pids) + 1)
        tkey = (s["pid"], s["tid"])
        tid = tids.setdefault(tkey, len(tids) + 1)
        args = {"trace_id": s["trace_id"], "span_id": s["span_id"]}
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        args.update(s.get("attrs") or {})
        events.append({"name": s["name"], "cat": s["cat"], "ph": "X",
                       "ts": s["ts"], "dur": max(s["dur"], 1.0),
                       "pid": pid, "tid": tid, "args": args})
    for label, pid in pids.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": label}})
    for (plabel, tlabel), tid in tids.items():
        events.append({"name": "thread_name", "ph": "M",
                       "pid": pids[plabel], "tid": tid,
                       "args": {"name": tlabel}})
    return events


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer
