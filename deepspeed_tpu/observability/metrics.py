"""Bounded process-wide metrics registry.

One registry per process, one declared tag schema (``observability.schema``),
three instrument kinds:

- :class:`Counter` — a cumulative total (emissions carry the running value,
  matching the existing ``*_total`` event streams);
- :class:`Gauge` — last-write-wins sample;
- :class:`Histogram` — **fixed log-bucket** distribution: O(1) memory however
  long the soak, p50/p95/p99 derived from bucket counts (the replacement for
  the grow-forever ``ttfts``/``tpots`` Python lists serving telemetry carried
  before PR 10).

``MonitorMaster`` is one export backend (attach with :meth:`MetricsRegistry.
attach_monitor`); Prometheus text exposition is another
(:meth:`MetricsRegistry.prometheus_text`, served by
:func:`start_metrics_server` behind ``deepspeed-serve --metrics-port``).
Telemetry emitters route their ``(tag, value, step)`` events through
:func:`record_events`, which is a no-op-cheap loop when nothing is attached.
"""

import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from . import schema

Event = Tuple[str, float, int]


class Counter:
    """Cumulative total. ``inc`` for owned counting, ``set_total`` when the
    emitter already tracks the running total (the existing event streams)."""

    kind = schema.COUNTER

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def set_total(self, v: float) -> None:
        # monotone: a replayed/stale event must not rewind the total
        if v > self.value:
            self.value = float(v)


class Gauge:
    kind = schema.GAUGE

    def __init__(self):
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Fixed log-bucket histogram: bucket ``i`` covers
    ``(lo * growth**(i-1), lo * growth**i]``, plus an underflow bucket for
    values ``<= lo`` (zeros and negatives land there too). Memory is one int64
    vector regardless of observation count; percentiles interpolate within the
    covering bucket, so relative error is bounded by ``growth - 1``.
    """

    kind = schema.HISTOGRAM

    def __init__(self, lo: float = 1e-3, hi: float = 1e7,
                 growth: float = 1.08):
        if not (lo > 0 and hi > lo and growth > 1):
            raise ValueError(f"bad histogram shape lo={lo} hi={hi} g={growth}")
        self.lo, self.growth = float(lo), float(growth)
        self._log_lo, self._log_g = math.log(lo), math.log(growth)
        n = int(math.ceil((math.log(hi) - self._log_lo) / self._log_g))
        self.counts = np.zeros(n + 2, np.int64)   # [underflow, n buckets, overflow]
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def _bucket(self, v: float) -> int:
        if v <= self.lo:
            return 0
        i = int(math.ceil((math.log(v) - self._log_lo) / self._log_g))
        return min(i, len(self.counts) - 1)

    def observe(self, v: float) -> None:
        v = float(v)
        self.counts[self._bucket(v)] += 1
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def bucket_upper_bounds(self) -> np.ndarray:
        n = len(self.counts)
        ups = self.lo * self.growth ** np.arange(n - 1)
        return np.concatenate([ups, [np.inf]])

    def percentile(self, q: float) -> Optional[float]:
        """Percentile ``q`` in [0, 100] from bucket counts (log-linear
        interpolation inside the covering bucket; clamped to observed
        min/max so tails stay honest)."""
        if self.count == 0:
            return None
        rank = q / 100.0 * (self.count - 1)
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if rank < cum + c:
                if i == 0:
                    est = self.lo
                elif i == len(self.counts) - 1:
                    est = self.max
                else:
                    hi = self.lo * self.growth ** i
                    lo = hi / self.growth
                    frac = (rank - cum + 0.5) / c
                    est = lo * (hi / lo) ** min(max(frac, 0.0), 1.0)
                return float(min(max(est, self.min), self.max))
            cum += c
        return float(self.max)

    @property
    def mean(self) -> Optional[float]:
        return self.total / self.count if self.count else None


_KIND_CLS = {schema.COUNTER: Counter, schema.GAUGE: Gauge,
             schema.HISTOGRAM: Histogram}


class MetricsRegistry:
    """Process-wide instrument table keyed by concrete tag.

    ``record(tag, value)`` consults the schema for the tag's kind and updates
    (or lazily creates) the matching instrument; an undeclared tag raises —
    the runtime face of the tag-schema lint. ``attach_monitor`` forwards every
    recorded event to a ``MonitorMaster``-shaped backend, making the legacy
    monitor fan-out one export path among several.
    """

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._monitors: List[object] = []
        self._lock = threading.Lock()

    # -------------------------------------------------------------- instruments
    def _get(self, tag: str, kind: Optional[str] = None):
        inst = self._metrics.get(tag)
        if inst is None:
            declared = schema.kind_of(tag)
            if kind is not None and kind != declared:
                raise TypeError(f"tag {tag!r} is declared {declared}, "
                                f"not {kind}")
            with self._lock:
                inst = self._metrics.setdefault(tag, _KIND_CLS[declared]())
        elif kind is not None and inst.kind != kind:
            raise TypeError(f"tag {tag!r} is a {inst.kind}, not {kind}")
        return inst

    def counter(self, tag: str) -> Counter:
        return self._get(tag, schema.COUNTER)

    def gauge(self, tag: str) -> Gauge:
        return self._get(tag, schema.GAUGE)

    def histogram(self, tag: str) -> Histogram:
        return self._get(tag, schema.HISTOGRAM)

    # ------------------------------------------------------------------ events
    def attach_monitor(self, monitor) -> None:
        if monitor is not None and monitor not in self._monitors:
            self._monitors.append(monitor)

    def detach_monitor(self, monitor) -> None:
        if monitor in self._monitors:
            self._monitors.remove(monitor)

    def record(self, tag: str, value: float, step: int = 0) -> None:
        inst = self._get(tag)
        if inst.kind == schema.COUNTER:
            inst.set_total(value)
        elif inst.kind == schema.GAUGE:
            inst.set(value)
        else:
            inst.observe(value)
        for m in self._monitors:
            if getattr(m, "enabled", False):
                m.write_events([(tag, float(value), int(step))])

    def record_events(self, events: Iterable[Event]) -> None:
        for tag, value, step in events:
            self.record(tag, value, step)

    # ---------------------------------------------------------------- export
    def snapshot(self) -> Dict[str, Dict]:
        out = {}
        for tag, inst in sorted(self._metrics.items()):
            if inst.kind == schema.HISTOGRAM:
                out[tag] = {"kind": inst.kind, "count": inst.count,
                            "sum": inst.total, "min": inst.min,
                            "max": inst.max,
                            "p50": inst.percentile(50),
                            "p95": inst.percentile(95),
                            "p99": inst.percentile(99)}
            else:
                out[tag] = {"kind": inst.kind, "value": inst.value}
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4. Tag paths map to metric
        names (``/`` and ``.`` become ``_``); the ``replica{i}`` segment maps
        to a ``replica`` label so per-replica series share one metric family."""
        lines: List[str] = []
        seen_meta = set()
        for tag in sorted(self._metrics):
            inst = self._metrics[tag]
            name, labels = _prom_name(tag)
            pattern = schema.resolve(tag)
            help_text = schema.TAGS[pattern][1] if pattern else ""
            if name not in seen_meta:
                seen_meta.add(name)
                lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} {inst.kind}")
            lab = ("{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"
                   if labels else "")
            if inst.kind == schema.HISTOGRAM:
                cum = 0
                for ub, c in zip(inst.bucket_upper_bounds(), inst.counts):
                    if c == 0 or math.isinf(ub):
                        continue
                    cum += int(c)
                    ext = ([*labels, ("le", f"{ub:.6g}")])
                    lines.append(
                        f"{name}_bucket{{"
                        + ",".join(f'{k}="{v}"' for k, v in ext)
                        + f"}} {cum}")
                lines.append(f"{name}_bucket{{"
                             + ",".join(f'{k}="{v}"'
                                        for k, v in [*labels, ("le", "+Inf")])
                             + f"}} {inst.count}")
                lines.append(f"{name}_sum{lab} {inst.total:.6g}")
                lines.append(f"{name}_count{lab} {inst.count}")
            else:
                v = inst.value if inst.value is not None else 0.0
                lines.append(f"{name}{lab} {v:.6g}")
        return "\n".join(lines) + "\n"


_REPLICA_SEG = re.compile(r"replica(\d+)")


def _prom_name(tag: str) -> Tuple[str, List[Tuple[str, str]]]:
    labels: List[Tuple[str, str]] = []

    def sub(m):
        labels.append(("replica", m.group(1)))
        return "replica"

    flat = _REPLICA_SEG.sub(sub, tag)
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", flat)
    return name.lower(), labels


class RegistryFeed:
    """Per-emitter bridge from cumulative event streams to the registry.

    Telemetry emitters publish *their own* running totals (``serving/
    completed_total`` restarts at 0 for every scheduler, and N router replicas
    each count privately). Feeding those straight into one process-wide
    counter makes ``/metrics`` a max-of-emitters, not a total — so each
    emitter owns a feed that remembers its last-reported value per counter
    tag and contributes the **delta**; the registry counter then sums across
    replicas and across successive runs. Gauges and histograms pass through
    unchanged (last-write / per-event semantics are already correct there).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry if registry is not None else _registry
        self._last: Dict[str, float] = {}

    def record_events(self, events: Iterable[Event]) -> None:
        reg = self._registry
        for tag, value, step in events:
            inst = reg._get(tag)
            if inst.kind == schema.COUNTER:
                prev = self._last.get(tag, 0.0)
                delta = float(value) - prev
                if delta > 0:
                    inst.inc(delta)
                self._last[tag] = float(value)
                for m in reg._monitors:
                    if getattr(m, "enabled", False):
                        m.write_events([(tag, float(value), int(step))])
            else:
                reg.record(tag, value, step)


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


def record_events(events: Iterable[Event]) -> None:
    """Module-level fast path for SINGLE-OWNER emitters (one engine per
    process publishing ``Train/*`` / ``inference/*``). Multi-instance
    emitters (per-replica serving/router telemetry) must use a
    :class:`RegistryFeed` so their counters sum instead of max-merging."""
    _registry.record_events(events)


# --------------------------------------------------------------- /metrics HTTP
def start_metrics_server(port: int, registry: Optional[MetricsRegistry] = None,
                         host: str = "127.0.0.1", status_provider=None,
                         health_provider=None):
    """Serve the observability HTTP plane on a daemon thread:

    - ``GET /metrics`` — Prometheus text exposition from ``registry``;
    - ``GET /statusz`` — live status JSON from ``status_provider()`` (replica
      health, outstanding work, pages, prefix hit rate, degradation rung,
      recent anomalies, last autoscale decisions — whatever the provider
      assembles); 404 when no provider is wired;
    - ``GET /healthz`` — liveness/readiness: ``health_provider()`` returns
      ``(ready, payload)``; the response is the payload JSON with status 200
      when ready, 503 when not. Without a provider the process being able to
      answer IS the liveness check: 200 ``{"live": true, "ready": true}``.

    Returns the ``http.server`` instance — ``server_port`` holds the bound
    port (pass ``port=0`` for an ephemeral one), ``shutdown()`` stops it."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry or _registry

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?")[0].rstrip("/")
            if path in ("", "/metrics"):
                self._send(200, reg.prometheus_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
                return
            if path == "/statusz":
                if status_provider is None:
                    self._send(404, b"no status provider wired\n",
                               "text/plain")
                    return
                try:
                    doc = status_provider()
                except Exception as e:   # a broken provider must not 500-loop
                    doc = {"error": f"{type(e).__name__}: {e}"}
                self._send(200, (_json.dumps(doc) + "\n").encode(),
                           "application/json")
                return
            if path == "/healthz":
                if health_provider is None:
                    ready, doc = True, {"live": True, "ready": True}
                else:
                    try:
                        ready, doc = health_provider()
                    except Exception as e:
                        ready, doc = False, {"live": True, "ready": False,
                                             "error":
                                             f"{type(e).__name__}: {e}"}
                self._send(200 if ready else 503,
                           (_json.dumps(doc) + "\n").encode(),
                           "application/json")
                return
            self.send_response(404)
            self.end_headers()

        def log_message(self, *args):     # stay quiet on the serving stdout
            pass

    server = ThreadingHTTPServer((host, int(port)), Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="ds-metrics-http").start()
    return server
