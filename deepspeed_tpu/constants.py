"""Config key constants and defaults.

Mirrors the JSON config surface of the reference (``deepspeed/runtime/constants.py``) so a
DeepSpeed user's config file keys carry over; values that are CUDA-only are accepted and ignored
with a warning rather than rejected.
"""

#############################################
# Batch-size triple (reference runtime/constants.py TRAIN_BATCH_SIZE et al.)
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_LOSS_SCALE = "loss_scale"
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_HYSTERESIS = "hysteresis"
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_AUTO_CAST = "auto_cast"
BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"
AMP = "amp"

#############################################
# Gradient clipping / misc training knobs
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
SPARSE_GRADIENTS = "sparse_gradients"
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
DUMP_STATE = "dump_state"
MEMORY_BREAKDOWN = "memory_breakdown"

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Parallelism (TPU-native addition: mesh axes in config)
#############################################
MESH = "mesh"  # {"data": -1, "fsdp": 1, "tensor": 1, "pipe": 1, "expert": 1, "seq": 1}
# comm-compute overlap: chunked collective matmuls + quantized collectives
COMM_OVERLAP = "comm_overlap"

#############################################
# Subsystems
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
COMMS_LOGGER = "comms_logger"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_WANDB = "wandb"
MONITOR_CSV = "csv_monitor"
MONITOR_JSONL = "jsonl_monitor"
FLOPS_PROFILER = "flops_profiler"
ELASTICITY = "elasticity"
COMPRESSION_TRAINING = "compression_training"
QUANTIZE_TRAINING = "quantize_training"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
PIPELINE = "pipeline"
AUTOTUNING = "autotuning"
AIO = "aio"
DATALOADER_DROP_LAST = "dataloader_drop_last"

#############################################
# Checkpoint
#############################################
CHECKPOINT = "checkpoint"
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"

#############################################
# Routing for progressive layer drop / eigenvalue
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
EIGENVALUE = "eigenvalue"

# Keys that exist in DeepSpeed configs but are CUDA-specific; accepted + ignored with a warning.
IGNORED_CUDA_ONLY_KEYS = (
    "communication_data_type",
    "disable_allgather",
    "fp16_master_weights_and_gradients",
)
