"""Metric event monitors: TensorBoard, W&B, CSV — fan-out via MonitorMaster.

Reference: ``deepspeed/monitor/monitor.py`` (``MonitorMaster:48``), ``tensorboard.py``,
``wandb.py``, ``csv_monitor.py``. Same event shape: a list of ``(tag, value, step)``
tuples written on rank 0 only (``Monitor.write_events`` dispatch). TPU-native notes: rank
comes from ``jax.process_index`` via the comm facade; values may be device arrays — they
are host-fetched once here, at the monitoring boundary, never in the train step.
"""

import atexit
import os
from typing import List, Optional, Tuple

from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:
    """Interface: ``write_events([(tag, value, step), ...])``; ``flush``/
    ``close`` default to no-ops so backends opt in."""

    enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


def _rank0() -> bool:
    from ..comm import comm as dist
    return dist.get_rank() == 0


class TensorBoardMonitor(Monitor):
    """Reference ``monitor/tensorboard.py``."""

    def __init__(self, config):
        self.enabled = bool(config.enabled) and _rank0()
        self.summary_writer = None
        if not self.enabled:
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
            log_dir = os.path.join(config.output_path or "./runs", config.job_name)
            os.makedirs(log_dir, exist_ok=True)
            self.summary_writer = SummaryWriter(log_dir=log_dir)
        except Exception as e:                                    # pragma: no cover
            logger.warning(f"tensorboard requested but unavailable ({e}); "
                           "events will be dropped")
            self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled:
            return
        for tag, value, step in event_list:
            self.summary_writer.add_scalar(tag, float(value), int(step))
        self.summary_writer.flush()

    def flush(self) -> None:
        if self.summary_writer is not None:
            self.summary_writer.flush()

    def close(self) -> None:
        if self.summary_writer is not None:
            self.summary_writer.close()
            self.summary_writer = None
        self.enabled = False


class WandbMonitor(Monitor):
    """Reference ``monitor/wandb.py``. Gated: wandb is optional."""

    def __init__(self, config):
        self.enabled = bool(config.enabled) and _rank0()
        if not self.enabled:
            return
        try:
            import wandb
            self._wandb = wandb
            wandb.init(project=config.project, group=config.group, entity=config.team)
        except Exception as e:
            logger.warning(f"wandb requested but unavailable ({e}); "
                           "events will be dropped")
            self.enabled = False

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled:
            return
        for tag, value, step in event_list:
            self._wandb.log({tag: float(value)}, step=int(step))


class csvMonitor(Monitor):
    """Reference ``monitor/csv_monitor.py`` (class name kept for parity): one CSV file per
    tag, rows ``step,value``."""

    def __init__(self, config):
        self.enabled = bool(config.enabled) and _rank0()
        if not self.enabled:
            return
        self.output_path = os.path.join(config.output_path or "./csv_monitor",
                                        config.job_name)
        os.makedirs(self.output_path, exist_ok=True)
        self._files = {}

    def _file_for(self, tag: str):
        if tag not in self._files:
            fname = tag.replace("/", "_") + ".csv"
            path = os.path.join(self.output_path, fname)
            new = not os.path.exists(path)
            f = open(path, "a", buffering=1)
            if new:
                f.write("step,value\n")
            self._files[tag] = f
        return self._files[tag]

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled:
            return
        for tag, value, step in event_list:
            self._file_for(tag).write(f"{int(step)},{float(value)}\n")

    def flush(self):
        for f in self._files.values():
            f.flush()

    def close(self):
        for f in self._files.values():
            f.close()
        self._files = {}
        self.enabled = False      # a write after close must not reopen files


class jsonlMonitor(Monitor):
    """Scrape-free metrics: one JSON object per event, appended to a single
    ``<job_name>.jsonl`` file — the serving-run backend (tail the file, no
    TensorBoard/W&B infrastructure). Naming follows ``csvMonitor``."""

    def __init__(self, config):
        self.enabled = bool(config.enabled) and _rank0()
        self._file = None
        if not self.enabled:
            return
        out_dir = config.output_path or "./jsonl_monitor"
        os.makedirs(out_dir, exist_ok=True)
        self.path = os.path.join(out_dir, config.job_name + ".jsonl")
        self._file = open(self.path, "a", buffering=1)

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled:
            return
        import json
        import time
        ts = time.time()
        for tag, value, step in event_list:
            self._file.write(json.dumps({"tag": tag, "value": float(value),
                                         "step": int(step), "ts": ts}) + "\n")

    def flush(self):
        if self._file is not None:
            self._file.flush()

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None
        self.enabled = False


class MonitorMaster(Monitor):
    """Dispatches events to every enabled backend, rank 0 only
    (reference ``monitor/monitor.py:48``)."""

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config
        self.tb_monitor: Optional[TensorBoardMonitor] = None
        self.wandb_monitor: Optional[WandbMonitor] = None
        self.csv_monitor: Optional[csvMonitor] = None
        self.jsonl_monitor: Optional[jsonlMonitor] = None
        if monitor_config.tensorboard.enabled:
            self.tb_monitor = TensorBoardMonitor(monitor_config.tensorboard)
        if monitor_config.wandb.enabled:
            self.wandb_monitor = WandbMonitor(monitor_config.wandb)
        if monitor_config.csv_monitor.enabled:
            self.csv_monitor = csvMonitor(monitor_config.csv_monitor)
        if getattr(monitor_config, "jsonl_monitor", None) is not None and \
                monitor_config.jsonl_monitor.enabled:
            self.jsonl_monitor = jsonlMonitor(monitor_config.jsonl_monitor)
        self.enabled = any(m is not None and m.enabled for m in self._backends())
        if self.enabled:
            # tail events must survive abrupt-but-clean exits: short runs end
            # before any backend buffer reaches a natural flush point
            atexit.register(self.close)

    def _backends(self):
        return (self.tb_monitor, self.wandb_monitor, self.csv_monitor,
                self.jsonl_monitor)

    def write_events(self, event_list: List[Event]) -> None:
        if not self.enabled or not event_list:
            return
        events = [(tag, float(value), int(step)) for tag, value, step in event_list]
        for m in self._backends():
            if m is not None and m.enabled:
                m.write_events(events)

    def flush(self) -> None:
        for m in self._backends():
            if m is not None and m.enabled:
                m.flush()

    def close(self) -> None:
        """Flush + close every backend (idempotent; also the atexit hook and
        the router-drain path)."""
        for m in self._backends():
            if m is not None:
                m.close()
        self.enabled = False
        atexit.unregister(self.close)
