from . import flops_profiler
