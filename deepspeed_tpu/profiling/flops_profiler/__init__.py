from .profiler import (FlopsProfiler, ProfileResult, get_model_profile, num_to_string, profile_fn)
