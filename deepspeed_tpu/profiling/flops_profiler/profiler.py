"""Flops profiler — XLA cost analysis + jaxpr walk.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py`` (``FlopsProfiler:20``,
``get_model_profile``). The reference monkey-patches ``torch.nn.functional`` and installs
forward hooks to count flops per module; on TPU both jobs are strictly easier and exact:

- totals come from the compiled executable's own cost model
  (``jax.stages.Compiled.cost_analysis()`` — flops, bytes accessed);
- the per-module breakdown walks the jaxpr: every equation carries the flax module name
  stack in its source info, so flops group by module path with no instrumentation.
"""

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...utils.logging import logger


# --------------------------------------------------------------- per-eqn flop estimates
def _dot_general_flops(eqn) -> float:
    (lhs, rhs), out = eqn.invars, eqn.outvars[0]
    dims = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dims
    lhs_shape = lhs.aval.shape
    contract = float(np.prod([lhs_shape[i] for i in lc])) if lc else 1.0
    out_elems = float(np.prod(out.aval.shape)) if out.aval.shape else 1.0
    return 2.0 * out_elems * contract


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0]
    rhs = eqn.invars[1]
    out_elems = float(np.prod(out.aval.shape))
    rhs_shape = rhs.aval.shape          # (out_ch, in_ch/g, *window)
    per_out = 2.0 * float(np.prod(rhs_shape[1:]))
    return out_elems * per_out


_FLOP_RULES: Dict[str, Callable] = {
    "dot_general": _dot_general_flops,
    "conv_general_dilated": _conv_flops,
}

# elementwise-ish primitives counted as 1 flop/element (the reference counts activations
# and norms the same way)
_ELEMENTWISE = {
    "add", "sub", "mul", "div", "max", "min", "exp", "log", "tanh", "logistic",
    "rsqrt", "sqrt", "erf", "neg", "abs", "pow", "integer_pow", "select_n",
}


def _eqn_flops(eqn) -> float:
    prim = eqn.primitive.name
    if prim in _FLOP_RULES:
        return _FLOP_RULES[prim](eqn)
    if prim in _ELEMENTWISE:
        out = eqn.outvars[0]
        return float(np.prod(out.aval.shape)) if out.aval.shape else 1.0
    if prim in ("pjit", "jit", "custom_jvp_call", "custom_vjp_call", "remat", "remat2",
                "checkpoint", "custom_vjp_call_jaxpr", "closed_call"):
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if inner is not None:
            jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
            return sum(_eqn_flops(e) for e in jaxpr.eqns)
    if prim == "scan":
        inner = eqn.params["jaxpr"].jaxpr
        return eqn.params["length"] * sum(_eqn_flops(e) for e in inner.eqns)
    if prim == "while":
        # loop trip count is dynamic; count one body iteration (documented limitation)
        inner = eqn.params["body_jaxpr"].jaxpr
        return sum(_eqn_flops(e) for e in inner.eqns)
    if prim == "cond":
        branches = eqn.params["branches"]
        return max((sum(_eqn_flops(e) for e in b.jaxpr.eqns) for b in branches),
                   default=0.0)
    return 0.0


def _eqn_scope(eqn, depth: int) -> str:
    """Module path of an equation from its flax name stack, truncated to ``depth``."""
    stack = str(eqn.source_info.name_stack)
    parts = [p for p in stack.split("/") if p and not p.startswith(("jit(", "jvp(",
                                                                   "transpose("))]
    if depth >= 0:
        parts = parts[:depth]
    return "/".join(parts) or "<toplevel>"


# --------------------------------------------------------------------------- public API
@dataclasses.dataclass
class ProfileResult:
    total_flops: float                       # analytical, from the jaxpr walk
    xla_flops: Optional[float]               # compiled-executable cost model (if exposed)
    bytes_accessed: Optional[float]
    params: int
    by_module: List[Tuple[str, float]]       # (module path, flops), descending

    def flops_str(self) -> str:
        return num_to_string(self.total_flops) + "FLOPs"


def num_to_string(num: float, precision: int = 2) -> str:
    """Reference ``profiler.py:num_to_string`` semantics (G/M/K suffixes)."""
    if num >= 1e12:
        return f"{num / 1e12:.{precision}f} T"
    if num >= 1e9:
        return f"{num / 1e9:.{precision}f} G"
    if num >= 1e6:
        return f"{num / 1e6:.{precision}f} M"
    if num >= 1e3:
        return f"{num / 1e3:.{precision}f} K"
    return f"{num:.{precision}f} "


def profile_fn(fn: Callable, *args, depth: int = 2, static_argnums=()) -> ProfileResult:
    """Profile one call of ``fn(*args)``: exact XLA totals + per-module jaxpr breakdown."""
    closed = jax.make_jaxpr(fn, static_argnums=static_argnums)(*args)

    by_module: Dict[str, float] = {}
    total = 0.0

    def walk(jaxpr):
        nonlocal total
        for eqn in jaxpr.eqns:
            inner = None
            if eqn.primitive.name in ("pjit", "jit", "closed_call"):
                inner = eqn.params.get("jaxpr")
            if inner is not None:
                walk(inner.jaxpr if hasattr(inner, "jaxpr") else inner)
                continue
            f = _eqn_flops(eqn)
            if f:
                total += f
                scope = _eqn_scope(eqn, depth)
                by_module[scope] = by_module.get(scope, 0.0) + f

    walk(closed.jaxpr)

    xla_flops = bytes_accessed = None
    try:
        compiled = jax.jit(fn, static_argnums=static_argnums).lower(*args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost:
            xla_flops = float(cost.get("flops", 0.0)) or None
            bytes_accessed = float(cost.get("bytes accessed", 0.0)) or None
    except Exception as e:                                        # pragma: no cover
        logger.debug(f"compiled cost_analysis unavailable: {e}")

    n_params = 0
    if args and (isinstance(args[0], dict) or hasattr(args[0], "keys")):
        try:
            n_params = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(args[0])
                           if hasattr(l, "shape"))
        except Exception:
            n_params = 0

    modules = sorted(by_module.items(), key=lambda kv: -kv[1])
    return ProfileResult(total_flops=total, xla_flops=xla_flops,
                         bytes_accessed=bytes_accessed, params=n_params,
                         by_module=modules)


def get_model_profile(model, args=(), kwargs=None, print_profile: bool = True,
                      detailed: bool = True, module_depth: int = -1,
                      top_modules: int = 1, as_string: bool = True):
    """Reference ``get_model_profile`` shape: returns (flops, macs, params).

    ``model`` is any callable (``fn(*args)``); for flax bundles pass
    ``lambda params, batch: module.apply(...)``.
    """
    kwargs = kwargs or {}
    fn = (lambda *a: model(*a, **kwargs)) if kwargs else model
    res = profile_fn(fn, *args, depth=module_depth if module_depth >= 0 else 2)
    if print_profile:
        lines = ["-" * 60,
                 "DeepSpeed-TPU Flops Profiler",
                 f"params:               {num_to_string(res.params)}",
                 f"fwd flops (jaxpr):    {num_to_string(res.total_flops)}FLOPs"]
        if res.xla_flops:
            lines.append(f"fwd flops (XLA):      {num_to_string(res.xla_flops)}FLOPs")
        if res.bytes_accessed:
            lines.append(f"bytes accessed:       {num_to_string(res.bytes_accessed)}B")
        if detailed:
            lines.append("per-module flops:")
            for name, f in res.by_module[:max(top_modules, 10)]:
                lines.append(f"  {name:<40} {num_to_string(f)}FLOPs")
        lines.append("-" * 60)
        logger.info("\n".join(lines))
    flops = res.total_flops
    macs = flops / 2.0
    params = res.params
    if as_string:
        return (num_to_string(flops) + "FLOPs", num_to_string(macs) + "MACs",
                num_to_string(params))
    return flops, macs, params


class FlopsProfiler:
    """Engine-integrated profiler (reference ``FlopsProfiler:20`` lifecycle:
    ``start_profile``/``stop_profile``/``print_model_profile``), driven by
    ``flops_profiler.profile_step`` in the config."""

    def __init__(self, config=None):
        self.config = config
        self.result: Optional[ProfileResult] = None

    def profile_step(self, fn: Callable, *args, depth: int = 2) -> ProfileResult:
        self.result = profile_fn(fn, *args, depth=depth)
        return self.result

    def print_model_profile(self, throughput_per_sec: Optional[float] = None):
        if self.result is None:
            return
        res = self.result
        lines = ["-" * 60, "DeepSpeed-TPU Flops Profiler (train step)",
                 f"step flops (jaxpr):  {num_to_string(res.total_flops)}FLOPs"]
        if res.xla_flops:
            lines.append(f"step flops (XLA):    {num_to_string(res.xla_flops)}FLOPs")
        if res.bytes_accessed:
            lines.append(f"bytes accessed:      {num_to_string(res.bytes_accessed)}B")
        if throughput_per_sec and res.total_flops:
            tf = res.total_flops * throughput_per_sec / 1e12
            lines.append(f"achieved TFLOPS:     {tf:.2f}")
        lines.append("per-module flops:")
        for name, f in res.by_module[:10]:
            lines.append(f"  {name:<40} {num_to_string(f)}FLOPs")
        lines.append("-" * 60)
        logger.info("\n".join(lines))
