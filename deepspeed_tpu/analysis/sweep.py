"""The ``bin/ds-tpu-lint`` whole-repo sweep: canonical traces + AST rules.

Runs every contract pass against the repo's *real* programs — not toys:

- **serving lane** — a tiny ``InferenceEngine`` (fp32 + int8-quantized) under
  a real :class:`ChunkedDecodeExecutor`: donation audit on the chunk /
  suffix-prefill / KV-pool movers, retrace lint across a repeated workload
  (the documented one-compile-per-key property), the dequant-hoist
  loop-invariance pin on BOTH decode bodies (while-loop generate and
  scan-lowered chunk), and the trace-time host-sync guard;
- **spec lane** — the speculative-decoding verify step under a speculating
  scheduler: one-compile-per-(slots, pages, page, cap, k, sampling) key
  across a grown-k workload (draft length is runtime data), donation audit
  on the verify fn's donated pool caches, dequant-hoist pin on the verify
  body's paged-writeback loop;
- **kvecon lane** — the tiered prefix cache's spill/promote movers under a
  real scheduler forced through device-evict→spill→promote traffic: a second
  identical workload must mint zero new mover compile keys (promote width is
  page-bounded, never per-request), the promote restore must actually donate
  the pool, and the spill gather must not donate it;
- **train lane** — a quantized-DP ``DeepSpeedEngine`` on the virtual CPU
  mesh: donation audit on the real ``train_step`` (state + EF residual),
  retrace lint across repeated steps;
- **overlap lane** — the ppermute-ring and monolithic collective matmuls:
  jaxpr-accounted bytes-on-wire cross-checked against ``CollectiveSpans``
  (including a deliberately twice-calling trace that pins per-site
  accumulation — the PR 3 overwrite class);
- **qring lane** — the fused quantized collective-matmul ring: intN payload
  bytes cross-checked three ways (span == closed form == jaxpr ppermute sum),
  the dequant-hoist structural pin (per-group scales dequant stays OUT of the
  ring step body), EF-residual donation, and a retrace pin on a forced-fused
  int8 tp=4 overlap engine;
- **AST lane** — bare-assert ban, emission-tag schema, hot-path host-sync
  rule over every library file (or only changed files in ``--changed-only``
  mode).

Everything runs offline on CPU (``JAX_PLATFORMS=cpu``, virtual 8-device
mesh); the report serializes to the JSON schema in :mod:`.report`.
"""

import os
import subprocess
from typing import List, Optional, Sequence

from .report import Finding, PassResult, Report, SEVERITY_ERROR

_TINY = dict(vocab_size=96, max_seq_len=64, n_embd=32, n_layer=2, n_head=4)
_CAP = 32


def _infra_result(name: str, target: str, exc: Exception) -> PassResult:
    r = PassResult(name, target, checked=0)
    r.findings.append(Finding(
        name, SEVERITY_ERROR, target,
        f"sweep lane crashed: {type(exc).__name__}: {exc}",
        {"exception": type(exc).__name__}))
    return r


# ------------------------------------------------------------- serving lane
def serving_lane(report: Report) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..inference.config import DeepSpeedInferenceConfig
    from ..inference.decode_fns import (build_decode_chunk, build_decode_loop,
                                        make_select_fn, make_slot_select_fn)
    from ..inference.engine import InferenceEngine
    from ..inference.serving.executor import ChunkedDecodeExecutor
    from ..models.causal_lm import gpt2_cfg, init_cache
    from ..parallel.mesh import set_global_mesh
    from .donation import donation_findings
    from .host_sync import trace_sync_findings
    from .jaxpr_passes import loop_body_findings
    from .retrace import CompileCacheLint

    cfg = gpt2_cfg(**_TINY, dtype=jnp.float32)
    engine = InferenceEngine(cfg, DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=_CAP))
    raw = jax.tree_util.tree_map(np.asarray, engine.params)
    engine_q = InferenceEngine((cfg, raw), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=_CAP,
        weight_quant={"enabled": True, "bits": 8}))

    # the legacy slot-row pool's movers, explicitly — the paged default's
    # contracts live in paged_lane
    ex = ChunkedDecodeExecutor(engine, slots=2, cap=_CAP, chunk_size=3,
                               kv_pool="slots")
    lint = CompileCacheLint(engine._fns, target="serving-engine")
    rng = np.random.default_rng(0)

    def workload():
        prompt = rng.integers(0, _TINY["vocab_size"], size=8).astype(np.int32)
        slot = ex.pool.acquire()
        tok0, _ = ex.prefill_into_slot(slot, prompt, seed=0)
        S = ex.slots
        state = dict(
            toks=np.full((S,), tok0, np.int32),
            lens=np.full((S,), 8, np.int32),
            active=np.array([True, False]),
            remaining=np.full((S,), 5, np.int32),
            eos=np.full((S,), -1, np.int32),
            seeds=np.zeros((S,), np.int32), steps=np.zeros((S,), np.int32))
        r = ex.run_chunk(state["toks"], state["lens"], state["active"],
                         state["remaining"], state["eos"], state["seeds"],
                         state["steps"])
        ex.run_chunk(r.toks[:, 0], r.lens, r.active, r.remaining,
                     state["eos"], state["seeds"], r.steps)
        ex.pool.release(slot)

    workload()                   # warmup: every key compiles exactly once
    lint.snapshot()
    workload()                   # identical shapes: zero new compiles allowed
    report.add(lint.findings())

    # donation: the real chunk fn + the pool's donated movers
    chunk_key = next(k for k in engine._fns if k[0] == "serve_chunk")
    S = ex.slots
    chunk_args = (engine.params, jnp.zeros((S, 1), jnp.int32), ex.pool.caches,
                  jnp.zeros((S,), jnp.int32), jnp.zeros((S,), bool),
                  jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
                  jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
                  ex._base_key)
    report.add(donation_findings(engine._fns[chunk_key], chunk_args,
                                 target="serve_chunk"))
    one = init_cache(cfg, 1, _CAP, dtype=engine.dtype)
    report.add(donation_findings(ex.pool._scatter_fn,
                                 (ex.pool.caches, one, 0),
                                 target="kv_pool.scatter"))
    report.add(donation_findings(ex.pool._zero_fn, (ex.pool.caches, 0),
                                 target="kv_pool.zero_fill"))
    # suffix prefill (prefix-cache hit path): donates the POOL through the jit
    sfn = ex._suffix_prefill_fn(8)
    sargs = (engine.params, ex.pool.caches, np.int32(0),
             jnp.zeros((1, 8), jnp.int32), jnp.asarray([4], jnp.int32),
             jnp.asarray([4], jnp.int32), jnp.asarray([0], jnp.int32),
             ex._base_key)
    report.add(donation_findings(sfn, sargs, target="serve_suffix_prefill"))

    # loop-invariance: dequant hoisted out of BOTH decode bodies (int8 engine)
    int8_invar = lambda a: getattr(a, "dtype", None) == jnp.int8  # noqa: E731

    def loop_pin(fn, args, site):
        findings, n_loops = loop_body_findings(
            fn, args, invar_predicate=int8_invar, what="dequant-hoist",
            site=site)
        res = PassResult("loop_invariance", site, findings, n_loops)
        if n_loops == 0:
            res.findings.append(Finding(
                "loop_invariance", SEVERITY_ERROR, site,
                "no loop found — the dequant-hoist pin target vanished"))
        report.add(res)

    select = make_select_fn(False, 1.0, 0, 1.0)
    caches = init_cache(cfg, 2, _CAP, dtype=engine_q.dtype)
    loop = build_decode_loop(engine_q.module, engine_q._dequant, select, _CAP,
                             overlap=engine_q.comm_overlap)
    largs = (engine_q.params, jnp.zeros((2, 1), jnp.int32), caches,
             jnp.full((2,), 8, jnp.int32), np.int32(8), np.int32(-1),
             jax.random.PRNGKey(0))
    loop_pin(loop, largs, "decode_loop")

    slot_select = make_slot_select_fn(False, 1.0, 0, 1.0)
    chunk = build_decode_chunk(engine_q.module, engine_q._dequant,
                               slot_select, 3,
                               overlap=engine_q.comm_overlap)
    qcaches = init_cache(cfg, 2, _CAP, dtype=engine_q.dtype)
    cargs = (engine_q.params, jnp.zeros((2, 1), jnp.int32), qcaches,
             jnp.full((2,), 8, jnp.int32), jnp.ones((2,), bool),
             jnp.full((2,), 5, jnp.int32), jnp.full((2,), -1, jnp.int32),
             jnp.zeros((2,), jnp.int32), jnp.zeros((2,), jnp.int32),
             jax.random.PRNGKey(0))
    loop_pin(chunk, cargs, "decode_chunk")

    # host-sync runtime guard: the traced chunk body performs zero transfers
    report.add(trace_sync_findings(chunk, cargs, target="decode_chunk"))
    set_global_mesh(None)


# ---------------------------------------------------------------- paged lane
def paged_lane(report: Report) -> None:
    """Paged-KV serving contracts: donation on the page-table chunk /
    suffix-prefill / scatter movers, and the one-compile-per-(slots, pages,
    page, chunk, sampling)-key property across a MIXED-LENGTH workload —
    page-count growth must ride the page table (runtime data), never mint a
    new compile key."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..inference.config import DeepSpeedInferenceConfig
    from ..inference.engine import InferenceEngine
    from ..inference.serving.executor import ChunkedDecodeExecutor
    from ..models.causal_lm import gpt2_cfg, init_cache
    from ..parallel.mesh import set_global_mesh
    from .donation import donation_findings
    from .retrace import CompileCacheLint

    cfg = gpt2_cfg(**_TINY, dtype=jnp.float32)
    engine = InferenceEngine(cfg, DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=_CAP))
    ex = ChunkedDecodeExecutor(engine, slots=2, cap=_CAP, chunk_size=3,
                               kv_pool="paged", kv_page_size=8)
    lint = CompileCacheLint(engine._fns, target="paged-serving-engine")
    rng = np.random.default_rng(0)

    def one_request(plen, new):
        prompt = rng.integers(0, _TINY["vocab_size"],
                              size=plen).astype(np.int32)
        slot = ex.pool.acquire(tokens=plen + new)
        tok0, _ = ex.prefill_into_slot(slot, prompt, seed=0)
        S = ex.slots
        active = np.zeros(S, bool)
        active[slot] = True
        lens = np.full((S,), plen, np.int32)
        r = ex.run_chunk(np.full((S,), tok0, np.int32), lens, active,
                         np.full((S,), new, np.int32),
                         np.full((S,), -1, np.int32), np.zeros(S, np.int32),
                         np.zeros(S, np.int32))
        ex.run_chunk(r.toks[:, 0], r.lens, r.active, r.remaining,
                     np.full((S,), -1, np.int32), np.zeros(S, np.int32),
                     r.steps)
        ex.pool.release(slot)

    def workload():
        one_request(8, 5)     # 2 pages
        one_request(20, 8)    # 4 pages: page growth, same chunk key

    workload()                # warmup: every key compiles exactly once
    lint.snapshot()
    workload()                # mixed lengths again: zero new compiles allowed
    report.add(lint.findings())

    chunk_key = next(k for k in engine._fns if k[0] == "serve_chunk_paged")
    S, mp = ex.slots, ex.pool.max_pages
    chunk_args = (engine.params, jnp.zeros((S, 1), jnp.int32), ex.pool.caches,
                  jnp.zeros((S, mp), jnp.int32), jnp.zeros((S,), jnp.int32),
                  jnp.zeros((S,), bool), jnp.zeros((S,), jnp.int32),
                  jnp.zeros((S,), jnp.int32), jnp.zeros((S,), jnp.int32),
                  jnp.zeros((S,), jnp.int32), ex._base_key)
    report.add(donation_findings(engine._fns[chunk_key], chunk_args,
                                 target="serve_chunk_paged"))
    one = init_cache(cfg, 1, _CAP, dtype=engine.dtype)
    report.add(donation_findings(ex.pool._scatter_fn,
                                 (ex.pool.caches, one,
                                  jnp.zeros((mp,), jnp.int32)),
                                 target="paged_pool.scatter"))
    sfn = ex._suffix_prefill_fn_paged(8)
    sargs = (engine.params, ex.pool.caches, jnp.zeros((mp,), jnp.int32),
             jnp.zeros((1, 8), jnp.int32), jnp.asarray([4], jnp.int32),
             jnp.asarray([4], jnp.int32), jnp.asarray([0], jnp.int32),
             ex._base_key)
    report.add(donation_findings(sfn, sargs,
                                 target="serve_suffix_prefill_paged"))
    set_global_mesh(None)


# ----------------------------------------------------------------- spec lane
def spec_lane(report: Report) -> None:
    """Speculative-decoding contracts: the one-compile-per-(slots, pages,
    page, cap, k, sampling)-key property across a GROWN-k workload (per-slot
    draft length is runtime data — a dry proposer, a cap-edge slot and a
    full-k window all ride the same compiled verify), donation audit on the
    verify fn's donated pool caches, and the dequant-hoist loop-invariance
    pin on the verify body's paged-writeback loop (int8 engine)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..inference.config import DeepSpeedInferenceConfig
    from ..inference.decode_fns import build_paged_spec_verify
    from ..inference.engine import InferenceEngine
    from ..inference.serving.scheduler import (ContinuousBatchingScheduler,
                                               ServingConfig)
    from ..parallel.mesh import set_global_mesh
    from ..models.causal_lm import gpt2_cfg
    from .donation import donation_findings
    from .jaxpr_passes import loop_body_findings
    from .retrace import CompileCacheLint

    cfg = gpt2_cfg(**_TINY, dtype=jnp.float32)
    engine = InferenceEngine(cfg, DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=_CAP))
    sched = ContinuousBatchingScheduler(engine, ServingConfig(
        slots=2, chunk_size=3, max_seq_len=_CAP, kv_pool="paged",
        kv_page_size=8, speculate=True, spec_k=4))
    lint = CompileCacheLint(engine._fns, target="spec-serving-engine")
    rng = np.random.default_rng(0)

    def workload():
        # a repetitive-suffix prompt (n-gram drafts fill the window) and a
        # random prompt (dry proposer, spec_len 0) through the SAME verify:
        # draft-length growth is runtime data, never a compile key
        rep = np.tile(rng.integers(0, _TINY["vocab_size"], size=4), 4) \
            .astype(np.int32)
        rnd = rng.integers(0, _TINY["vocab_size"], size=12).astype(np.int32)
        hs = [sched.submit(rep, max_new_tokens=6),
              sched.submit(rnd, max_new_tokens=6)]
        sched.run()
        if any(h.finish_reason != "length" for h in hs):
            raise RuntimeError("spec_lane workload did not complete")

    workload()                # warmup: every key compiles exactly once
    lint.snapshot()
    workload()                # grown/shrunk drafts: zero new compiles allowed
    report.add(lint.findings())

    ex = sched.executor
    vkey = next(k for k in engine._fns if k[0] == "serve_spec_verify_paged")
    k = vkey[5]
    S, mp = ex.slots, ex.pool.max_pages
    vargs = (engine.params, jnp.zeros((S, k + 1), jnp.int32), ex.pool.caches,
             jnp.zeros((S, mp), jnp.int32), jnp.zeros((S,), jnp.int32),
             jnp.ones((S,), jnp.int32), jnp.zeros((S,), bool))
    report.add(donation_findings(engine._fns[vkey], vargs,
                                 target="serve_spec_verify_paged"))

    # loop-invariance: dequant hoisted out of the verify body's paged
    # KV-writeback loop (int8 engine) — the spec analogue of the decode pins
    raw = jax.tree_util.tree_map(np.asarray, engine.params)
    engine_q = InferenceEngine((cfg, raw), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=_CAP,
        weight_quant={"enabled": True, "bits": 8}))
    from ..inference.serving.executor import ChunkedDecodeExecutor
    exq = ChunkedDecodeExecutor(engine_q, slots=2, cap=_CAP, chunk_size=3,
                                kv_pool="paged", kv_page_size=8)
    verify = build_paged_spec_verify(engine_q.module, engine_q._dequant,
                                     kv_cap=_CAP,
                                     overlap=engine_q.comm_overlap)
    int8_invar = lambda a: getattr(a, "dtype", None) == jnp.int8  # noqa: E731
    qargs = (engine_q.params, jnp.zeros((S, k + 1), jnp.int32),
             exq.pool.caches, jnp.zeros((S, exq.pool.max_pages), jnp.int32),
             jnp.zeros((S,), jnp.int32), jnp.ones((S,), jnp.int32),
             jnp.zeros((S,), bool))
    findings, n_loops = loop_body_findings(
        verify, qargs, invar_predicate=int8_invar, what="dequant-hoist",
        site="spec_verify")
    res = PassResult("loop_invariance", "spec_verify", findings, n_loops)
    if n_loops == 0:
        res.findings.append(Finding(
            "loop_invariance", SEVERITY_ERROR, "spec_verify",
            "no loop found — the dequant-hoist pin target vanished"))
    report.add(res)
    set_global_mesh(None)


# --------------------------------------------------------------- kvecon lane
def kvecon_lane(report: Report) -> None:
    """Tiered prefix-cache contracts (PR 19): the spill/promote movers —
    ``gather_pages`` at device-LRU eviction, ``promote_prefix``'s restore at
    host→device promote — are module-level jit singletons keyed only by row
    count, so a second identical spill→promote workload must mint ZERO new
    compile entries (no per-promote keys); the restore side must actually
    donate the pool (no silent copy-fallback), and the gather side must NOT
    donate it (the spilled entry's source pages stay live for readers)."""
    import jax.numpy as jnp
    import numpy as np
    from ..inference.config import DeepSpeedInferenceConfig
    from ..inference.engine import InferenceEngine
    from ..inference.serving import kv_pool as kvp
    from ..inference.serving.prefix_cache import PrefixCacheConfig
    from ..inference.serving.scheduler import (ContinuousBatchingScheduler,
                                               ServingConfig)
    from ..models.causal_lm import gpt2_cfg
    from ..parallel.mesh import set_global_mesh
    from .donation import _flat_args_info, donation_findings

    cfg = gpt2_cfg(**_TINY, dtype=jnp.float32)
    engine = InferenceEngine(cfg, DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=_CAP))
    # HBM budget sized for exactly ONE prompt-length entry: the second insert
    # evicts the first, which spills to the (generous) host rung; re-serving
    # the first prefix then promotes it back — the canonical tier traffic
    sched = ContinuousBatchingScheduler(engine, ServingConfig(
        slots=2, chunk_size=2, max_seq_len=_CAP, kv_pool="paged",
        kv_page_size=4,
        prefix_cache=PrefixCacheConfig(
            max_bytes=12 * 1024, host_tier_bytes=1 << 20,
            min_hit_tokens=4, min_insert_tokens=4, insert_on="prefill")))
    rng = np.random.default_rng(7)
    pa = rng.integers(0, _TINY["vocab_size"], size=16).astype(np.int32)
    pb = rng.integers(0, _TINY["vocab_size"], size=16).astype(np.int32)

    def serve(prompt):
        h = sched.submit(prompt, max_new_tokens=2)
        sched.run()
        if h.finish_reason != "length":
            raise RuntimeError("kvecon_lane workload did not complete")

    def workload():
        serve(pa)               # insert A (fills the device budget)
        serve(pb)               # insert B -> A evicts -> spills (gather)
        serve(pa)               # A: host hit -> promote (restore)

    workload()
    pc = sched.prefix_cache
    s = pc.stats()
    wired = PassResult("retrace", "tiered-prefix-movers", checked=2)
    if s["spills"] < 1 or s["promotions"] < 1:
        wired.findings.append(Finding(
            "retrace", SEVERITY_ERROR, "tiered-prefix-movers",
            f"spill/promote workload exercised neither mover "
            f"(spills={s['spills']} promotions={s['promotions']}) — the "
            "lane's pin targets vanished"))
    g0 = kvp._paged_gather_jit.cache_info().currsize
    r0 = kvp._paged_restore_jit.cache_info().currsize
    workload()                  # identical traffic: zero new compile keys
    g1 = kvp._paged_gather_jit.cache_info().currsize
    r1 = kvp._paged_restore_jit.cache_info().currsize
    if (g1, r1) != (g0, r0):
        wired.findings.append(Finding(
            "retrace", SEVERITY_ERROR, "tiered-prefix-movers",
            f"a second identical spill/promote workload minted new mover "
            f"compile keys (gather {g0}->{g1}, restore {r0}->{r1}) — "
            "promote width must stay page-bounded, never per-request"))
    report.add(wired)

    # donation: the promote restore donates the pool; the spill gather must
    # not (it reads pages the trie may still share with in-flight slots)
    pool = sched.executor.pool
    slot = pool.acquire(tokens=8)
    n = pool.pages_for(8)
    tbl = jnp.asarray(np.asarray(pool.page_table[slot, :n], np.int32))
    R = n * pool.page_size
    slab = pool.gather_pages(np.asarray(pool.page_table[slot, :n]), R)
    report.add(donation_findings(kvp._paged_restore_jit(R),
                                 (pool.caches, slab, tbl),
                                 target="paged_restore(promote)"))
    gres = PassResult("donation", "paged_gather(spill)", checked=1)
    lowered = kvp._paged_gather_jit(R).lower(pool.caches, tbl)
    donated = [p for p, info in _flat_args_info(lowered) if info.donated]
    if donated:
        gres.findings.append(Finding(
            "donation", SEVERITY_ERROR, "paged_gather(spill)",
            f"spill gather donates {donated[:4]} — the gathered pages stay "
            "referenced by live slots and the trie; donation here would "
            "poison the pool at eviction time"))
    report.add(gres)
    pool.release(slot)
    set_global_mesh(None)


# --------------------------------------------------------------- train lane
def train_lane(report: Report) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..models import GPT2Config, gpt2_model
    from ..parallel.mesh import MeshSpec, set_global_mesh
    from ..runtime.engine import DeepSpeedEngine
    from .donation import donation_findings
    from .retrace import CompileCacheLint

    devices = jax.devices()
    if len(devices) < 8:
        r = PassResult("retrace", "train-engine", checked=0)
        r.findings.append(Finding(
            "retrace", SEVERITY_ERROR, "train-engine",
            f"virtual mesh needs 8 devices, found {len(devices)} — run via "
            "bin/ds-tpu-lint (it sets xla_force_host_platform_device_count)"))
        report.add(r)
        return
    set_global_mesh(None)
    cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=32, n_layer=2,
                     n_head=4, dropout=0.0, dtype=jnp.float32,
                     scan_layers=True)
    engine = DeepSpeedEngine(
        model=gpt2_model(cfg, sample_seq_len=32),
        config={"train_batch_size": 16, "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 0},
                "comm_overlap": {"enabled": True,
                                 "quantized_allreduce": True},
                "steps_per_print": 10**9},
        mesh_spec=MeshSpec({"data": 8}, devices))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, size=(16, 32),
                                       dtype=np.int32)}
    lint = CompileCacheLint(engine._fns, target="train-engine")
    engine.train_batch(batch)
    lint.snapshot()
    engine.train_batch(batch)
    report.add(lint.findings())

    gbatch = engine._globalize(engine._reshape_for_gas(batch),
                               leading_gas=True)
    args = (engine.state, gbatch, np.float32(1e-2), np.float32(1.0),
            engine._qar_residual)
    report.add(donation_findings(engine._fns["train_step"], args,
                                 target="train_step_quantized"))
    set_global_mesh(None)


# ------------------------------------------------------------- overlap lane
def overlap_lane(report: Report) -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..parallel import overlap as ov
    from ..parallel.mesh import AXIS_TENSOR, MeshSpec
    from ..utils.jax_compat import shard_map
    from .collectives import crosscheck_findings

    devices = jax.devices()
    if len(devices) < 4:
        r = PassResult("collective_schema", "overlap-ring", checked=0)
        r.findings.append(Finding(
            "collective_schema", SEVERITY_ERROR, "overlap-ring",
            f"need 4 devices for the ring lane, found {len(devices)}"))
        report.add(r)
        return
    mesh = MeshSpec({"tensor": 4}, devices[:4])
    ag_specs = dict(mesh=mesh.mesh, axis_names={AXIS_TENSOR},
                    in_specs=(P(AXIS_TENSOR, None), P(None, None)),
                    out_specs=P(None, None), check_vma=False)
    rs_specs = dict(mesh=mesh.mesh, axis_names={AXIS_TENSOR},
                    in_specs=(P(None, AXIS_TENSOR), P(AXIS_TENSOR, None)),
                    out_specs=P(AXIS_TENSOR, None), check_vma=False)
    x = jnp.ones((8, 16), jnp.float32)
    w = jnp.ones((16, 6), jnp.float32)

    lanes = [
        ("ring_allgather_matmul", ag_specs, (x, w),
         lambda a, b: ov.chunked_allgather_matmul(
             a, b, AXIS_TENSOR, site="lint.ring_ag")),
        ("ring_matmul_reduce_scatter", rs_specs, (x, w),
         lambda a, b: ov.chunked_matmul_reduce_scatter(
             a, b, AXIS_TENSOR, site="lint.ring_rs")),
        ("monolithic_allgather_matmul", ag_specs, (x, w),
         lambda a, b: ov.allgather_matmul_monolithic(
             a, b, AXIS_TENSOR, site="lint.mono_ag")),
        ("monolithic_matmul_reduce_scatter", rs_specs, (x, w),
         lambda a, b: ov.matmul_reduce_scatter_monolithic(
             a, b, AXIS_TENSOR, site="lint.mono_rs")),
        # one site traced twice in a single program: pins ACCUMULATION of
        # bytes_total across traces (the PR 3 last-call-overwrite class)
        ("ring_site_accumulation", ag_specs, (x, w),
         lambda a, b: ov.chunked_allgather_matmul(
             a, b, AXIS_TENSOR, site="lint.ring_twice")
         + ov.chunked_allgather_matmul(
             a, b, AXIS_TENSOR, site="lint.ring_twice")),
    ]
    for name, specs, args, body in lanes:
        fn = shard_map(body, **specs)
        report.add(crosscheck_findings(fn, args, site_prefixes=("lint.",),
                                       target=name))


# ------------------------------------------------------------------ qring lane
def qring_lane(report: Report) -> None:
    """Fused-quantized-ring contracts (``parallel/qring.py``):

    - **collective schema** — the intN ring payload at wire widths 8 and 4:
      the recorded span, the closed form
      :func:`collectives.qring_wire_bytes`, and the jaxpr ppermute-operand
      sum must agree to the byte (bytes-on-wire claims are never
      hand-computed);
    - **dequant hoist** — on the XLA (unfused) ring path the per-group-scales
      weight dequant happens once per column direction OUTSIDE the ring
      steps. The ring is python-unrolled (no ``lax`` loop for
      ``loop_body_findings`` to inspect), so the pin is structural: count
      the weight-slab int8→f32 converts in the jaxpr — ``dequantize_grouped``
      converts the 3-D ``(groups, g, n)`` regrouped slab, while the wire
      decompress converts 2-D ``(blocks, block)`` payloads, so the two are
      shape-distinguishable. Hoisted = one per direction; ``W`` per
      direction = the dequant leaked into the step body;
    - **EF-residual donation** — a caller threading the residual across
      dispatches (the cumulative-EF regime) gets in-place buffer reuse, read
      off the executable's ``input_output_alias`` table;
    - **retrace** — a forced-fused int8 tp=4 overlap engine (the deployable
      qring decode config): two identical generates mint zero new compile
      keys on the fused ring movers.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    from ..inference.config import DeepSpeedInferenceConfig
    from ..inference.engine import InferenceEngine
    from ..models.causal_lm import gpt2_cfg
    from ..ops.quantizer.quant import quantize_grouped
    from ..parallel import qring
    from ..parallel.mesh import AXIS_TENSOR, MeshSpec, set_global_mesh
    from ..utils.comms_logging import collective_spans
    from ..utils.jax_compat import shard_map
    from .collectives import crosscheck_findings, qring_wire_bytes
    from .donation import donation_findings
    from .jaxpr_passes import subjaxprs
    from .retrace import CompileCacheLint

    devices = jax.devices()
    if len(devices) < 4:
        r = PassResult("collective_schema", "qring", checked=0)
        r.findings.append(Finding(
            "collective_schema", SEVERITY_ERROR, "qring",
            f"need 4 devices for the qring lane, found {len(devices)}"))
        report.add(r)
        return
    W = 4
    mesh = MeshSpec({"tensor": W}, devices[:W])
    m, k, n, blk = 8, 32, 12, 16
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.float32)
    q, s = quantize_grouped(
        jnp.asarray(rng.standard_normal((k, n)), jnp.float32),
        group_size=8, bits=8)

    def ring(wire_bits, site):
        def body(xl, ql, sl):
            out, _ = qring.fused_quant_matmul_reduce_scatter(
                xl, ql, sl, AXIS_TENSOR, bits=8, wire_bits=wire_bits,
                quant_block=blk, site=site)
            return out
        return shard_map(body, mesh=mesh.mesh, axis_names={AXIS_TENSOR},
                         in_specs=(P(None, AXIS_TENSOR),
                                   P(AXIS_TENSOR, None),
                                   P(AXIS_TENSOR, None)),
                         out_specs=P(AXIS_TENSOR, None), check_vma=False)

    # wire-bytes cross-check: span == closed form == jaxpr, to the byte
    for wb in (8, 4):
        site = f"lint.qring_w{wb}"
        before = collective_spans.summary().get(site, {}).get(
            "bytes_total", 0)
        res = crosscheck_findings(ring(wb, site), (x, q, s),
                                  site_prefixes=("lint.",),
                                  target=f"qring-wire{wb}")
        recorded = collective_spans.summary().get(site, {}).get(
            "bytes_total", 0) - before
        closed = qring_wire_bytes(m, n, W, wire_bits=wb, block=blk,
                                  bidirectional=True)
        if recorded != closed:
            res.findings.append(Finding(
                "collective_schema", SEVERITY_ERROR, f"qring-wire{wb}",
                f"recorded ring span {recorded} B != closed-form "
                f"qring_wire_bytes {closed} B — the wire-bytes model and "
                "the ring's recording drifted apart",
                {"recorded": int(recorded), "closed_form": int(closed)}))
        report.add(res)

    # dequant-hoist pin (structural; see docstring for the shape argument)
    def n_weight_dequants(jx) -> int:
        cnt = 0
        for eqn in jx.eqns:
            if eqn.primitive.name == "convert_element_type":
                av = getattr(eqn.invars[0], "aval", None)
                if av is not None and av.dtype == jnp.int8 and av.ndim == 3:
                    cnt += 1
            for sub in subjaxprs(eqn):
                cnt += n_weight_dequants(sub)
        return cnt

    n_deq = n_weight_dequants(jax.make_jaxpr(ring(8, None))(x, q, s).jaxpr)
    res = PassResult("loop_invariance", "qring-dequant-hoist", checked=1)
    if n_deq == 0:
        res.findings.append(Finding(
            "loop_invariance", SEVERITY_ERROR, "qring-dequant-hoist",
            "no weight-slab int8->f32 convert in the ring trace — the "
            "dequant-hoist pin target vanished (fused backend forced under "
            "the lint sweep, or dequantize_grouped restructured?)"))
    elif n_deq > 2:
        res.findings.append(Finding(
            "loop_invariance", SEVERITY_ERROR, "qring-dequant-hoist",
            f"{n_deq} weight-slab dequant converts in the ring trace — "
            "expected one per column direction (2, bidirectional): the "
            "per-group-scales dequant leaked into the ring step body and "
            "re-materialises the fp weight every hop",
            {"converts": int(n_deq)}))
    report.add(res)

    # EF-residual donation: threading callers reuse the buffer in place
    res0 = jnp.zeros((m // W * n * W,), jnp.float32)

    def body_res(xl, ql, sl, rl):
        return qring.fused_quant_matmul_reduce_scatter(
            xl, ql, sl, AXIS_TENSOR, bits=8, wire_bits=8, quant_block=blk,
            residual=rl)

    ring_res = shard_map(body_res, mesh=mesh.mesh, axis_names={AXIS_TENSOR},
                         in_specs=(P(None, AXIS_TENSOR), P(AXIS_TENSOR, None),
                                   P(AXIS_TENSOR, None), P(AXIS_TENSOR)),
                         out_specs=(P(AXIS_TENSOR, None), P(AXIS_TENSOR)),
                         check_vma=False)
    report.add(donation_findings(ring_res, (x, q, s, res0),
                                 donate_argnums=(3,),
                                 target="qring.residual"))

    # forced-fused int8 tp=4 overlap engine: retrace pin on the ring movers
    prev = os.environ.get("DS_TPU_WQ_FORCE_FUSED")
    os.environ["DS_TPU_WQ_FORCE_FUSED"] = "1"
    try:
        cfg = gpt2_cfg(**_TINY, dtype=jnp.float32)
        engine = InferenceEngine(cfg, DeepSpeedInferenceConfig(
            dtype="float32", max_out_tokens=_CAP,
            weight_quant={"enabled": True, "bits": 8, "group": 8},
            tensor_parallel={"tp_size": 4},
            comm_overlap={"enabled": True, "chunk_bits": 8,
                          "quant_block": 16}))
        ids = np.asarray(
            rng.integers(0, _TINY["vocab_size"], size=(8, 8)), np.int32)
        lint = CompileCacheLint(engine._fns, target="qring-engine")
        engine.generate(ids, max_new_tokens=4)
        lint.snapshot()
        engine.generate(ids, max_new_tokens=4)
        report.add(lint.findings())
    finally:
        if prev is None:
            os.environ.pop("DS_TPU_WQ_FORCE_FUSED", None)
        else:
            os.environ["DS_TPU_WQ_FORCE_FUSED"] = prev
        set_global_mesh(None)


# ------------------------------------------------------------------ AST lane
def ast_lane(report: Report, repo_root: str,
             paths: Optional[Sequence[str]] = None) -> None:
    from ..observability.schema import emission_tag_rule
    from .ast_rules import BareAssertRule, run_ast_rules
    from .host_sync import HOT_PATH_SPECS, hot_path_sync_findings
    report.add(run_ast_rules(repo_root,
                             [BareAssertRule(), emission_tag_rule()],
                             paths=paths))
    if paths is None:
        report.add(hot_path_sync_findings(repo_root))
    else:
        specs = [s for s in HOT_PATH_SPECS if s.path in set(paths)]
        if specs:
            report.add(hot_path_sync_findings(repo_root, specs))


# -------------------------------------------------------------------- driver
def changed_files(repo_root: str, base: str = "HEAD") -> List[str]:
    """Repo-relative changed ``deepspeed_tpu/*.py`` paths vs ``base`` —
    including UNTRACKED files (a brand-new module is exactly what a
    pre-commit lint run must check); empty when git is unavailable.
    NUL-separated so paths with whitespace survive."""
    cmds = (
        ["git", "diff", "--name-only", "-z", base, "--", "deepspeed_tpu"],
        ["git", "ls-files", "--others", "--exclude-standard", "-z", "--",
         "deepspeed_tpu"],
    )
    paths: List[str] = []
    for cmd in cmds:
        try:
            out = subprocess.run(cmd, cwd=repo_root, capture_output=True,
                                 text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return []
        if out.returncode != 0:
            continue
        paths.extend(p for p in out.stdout.split("\0")
                     if p.endswith(".py") and p not in paths)
    return paths


def run_sweep(repo_root: str, *, ast_only: bool = False,
              paths: Optional[Sequence[str]] = None) -> Report:
    report = Report()
    ast_lane(report, repo_root, paths=paths)
    if not ast_only:
        for lane in (serving_lane, paged_lane, spec_lane, kvecon_lane,
                     train_lane, overlap_lane, qring_lane):
            try:
                lane(report)
            except Exception as e:  # a crashed lane is a failed sweep
                report.add(_infra_result(lane.__name__, "sweep", e))
    return report


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI body for ``bin/ds-tpu-lint`` (env already prepared there)."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="ds-tpu-lint",
        description="Program-contract analyzer: donation / retrace / "
                    "host-sync / loop-invariance / collective-schema passes "
                    "over the repo's canonical traces, plus AST rules.")
    parser.add_argument("--json", metavar="PATH",
                        help="write the JSON report to PATH ('-' = stdout)")
    parser.add_argument("--ast-only", action="store_true",
                        help="skip the traced lanes (fast source-only mode)")
    parser.add_argument("--changed-only", nargs="?", const="HEAD",
                        metavar="BASE",
                        help="AST rules on files changed vs BASE "
                             "(default HEAD); implies --ast-only")
    parser.add_argument("--repo-root", default=None)
    args = parser.parse_args(argv)

    repo_root = args.repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    paths = None
    ast_only = args.ast_only
    if args.changed_only is not None:
        paths = changed_files(repo_root, args.changed_only)
        ast_only = True
        if not paths:
            print("ds-tpu-lint: no changed deepspeed_tpu/*.py files vs "
                  f"{args.changed_only}")
    import sys
    if args.json == "-":
        # stdout must carry ONLY the report so `--json -` pipes cleanly:
        # the traced lanes' engine logs default to stdout — move them
        from ..utils.logging import logger as ds_logger
        for handler in ds_logger.handlers:
            if getattr(handler, "stream", None) is sys.stdout:
                handler.stream = sys.stderr
    report = run_sweep(repo_root, ast_only=ast_only, paths=paths)
    if args.json == "-":
        print(report.to_json())
        print(report.summary(), file=sys.stderr)
    else:
        if args.json:
            with open(args.json, "w") as f:
                f.write(report.to_json())
            print(f"ds-tpu-lint: report written to {args.json}")
        print(report.summary())
    return 0 if report.ok else 1
