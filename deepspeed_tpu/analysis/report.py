"""Findings, per-pass results, and the JSON report ``bin/ds-tpu-lint`` emits.

One :class:`Finding` is one contract violation (or advisory note) anchored to
a site — a ``path:line`` for AST rules, a ``program/site`` name for traced
passes. A :class:`PassResult` groups one pass's findings over one target with
a count of units it inspected (so "0 findings" is distinguishable from "never
looked"). :class:`Report` aggregates pass results and serializes to the JSON
schema the lint smoke test pins:

.. code-block:: json

    {"version": 1, "ok": false, "n_errors": 1, "n_warnings": 0,
     "passes": [{"name": "donation", "target": "serve_chunk", "checked": 12,
                 "findings": [{"pass": "donation", "severity": "error",
                               "site": "...", "message": "...",
                               "details": {}}]}]}
"""

from dataclasses import dataclass, field
from typing import Any, Dict, List

import json

SEVERITY_ERROR = "error"      # contract violated: lint exits nonzero
SEVERITY_WARNING = "warning"  # suspicious but allowlisted/ambiguous
SEVERITY_INFO = "info"        # advisory context (never fails the sweep)

_SEVERITIES = (SEVERITY_ERROR, SEVERITY_WARNING, SEVERITY_INFO)


@dataclass
class Finding:
    """One contract violation, anchored to a site."""
    pass_name: str
    severity: str
    site: str
    message: str
    details: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.severity not in _SEVERITIES:
            raise ValueError(f"severity must be one of {_SEVERITIES}, "
                             f"got {self.severity!r}")

    def to_dict(self) -> Dict[str, Any]:
        return {"pass": self.pass_name, "severity": self.severity,
                "site": self.site, "message": self.message,
                "details": dict(self.details)}

    def __str__(self):
        return f"[{self.pass_name}] {self.severity}: {self.site}: {self.message}"


@dataclass
class PassResult:
    """One pass's findings over one target."""
    name: str
    target: str
    findings: List[Finding] = field(default_factory=list)
    #: units inspected (donated leaves, cached fns, AST files, collective
    #: eqns ...) — lets a report distinguish "clean" from "vacuous"
    checked: int = 0

    @property
    def ok(self) -> bool:
        return not any(f.severity == SEVERITY_ERROR for f in self.findings)

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "target": self.target,
                "checked": int(self.checked),
                "findings": [f.to_dict() for f in self.findings]}


class Report:
    """Aggregate of pass results; the sweep's exit status and JSON artifact."""

    VERSION = 1

    def __init__(self):
        self.results: List[PassResult] = []

    def add(self, result: PassResult) -> PassResult:
        self.results.append(result)
        return result

    def findings(self, severity: str = None) -> List[Finding]:
        out = [f for r in self.results for f in r.findings]
        if severity is not None:
            out = [f for f in out if f.severity == severity]
        return out

    @property
    def n_errors(self) -> int:
        return len(self.findings(SEVERITY_ERROR))

    @property
    def n_warnings(self) -> int:
        return len(self.findings(SEVERITY_WARNING))

    @property
    def ok(self) -> bool:
        return self.n_errors == 0

    def to_dict(self) -> Dict[str, Any]:
        return {"version": self.VERSION, "ok": self.ok,
                "n_errors": self.n_errors, "n_warnings": self.n_warnings,
                "passes": [r.to_dict() for r in self.results]}

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def summary(self) -> str:
        lines = [f"ds-tpu-lint: {len(self.results)} pass runs, "
                 f"{self.n_errors} errors, {self.n_warnings} warnings"]
        for r in self.results:
            status = "ok" if r.ok else "FAIL"
            lines.append(f"  {status:4s} {r.name:<18s} {r.target} "
                         f"(checked {r.checked})")
            for f in r.findings:
                lines.append(f"       - {f}")
        return "\n".join(lines)
