"""Retrace / compile-cache lint: one compile per cache key, checked.

The serving/runtime engines carry an explicit compile cache (``engine._fns``):
the documented property is ONE compile per ``(slots, cap, chunk, sampling)``
(executor chunk), per ``(prompt-bucket, cap, sampling)`` (prefill), per
suffix bucket, per train-step build. A retrace inside one cached entry —
weak-type promotion (a python int where an ``np.int32`` belonged), dtype or
shape drift, a non-hashable static argument forcing cache misses — silently
doubles compile time and HBM, and on the serving hot path reads as a wedged
replica (the PR 8 watchdog false-kill class). jax exposes the per-function
compile count as ``jitted._cache_size()``; this lint walks a cache dict,
snapshots counts, and fails when any entry exceeds its budget or grows
between snapshots.
"""

from typing import Any, Dict, Iterator, List, Tuple

from .report import Finding, PassResult, SEVERITY_ERROR, SEVERITY_WARNING


class RetraceError(AssertionError):
    """A cached compiled fn retraced (weak-type/shape drift)."""

    def __init__(self, findings: List[Finding]):
        self.findings = list(findings)
        super().__init__("retrace contract violated: " +
                         "; ".join(f.message for f in findings[:6]))


def _iter_jitted(value, prefix: str) -> Iterator[Tuple[str, Any]]:
    """Yield ``(label, jitted_fn)`` for every compiled fn inside a cache
    value (entries may be a jitted fn, a tuple of them — e.g. the generate
    path's ``(prefill, decode_loop)`` — or a nested dict)."""
    if hasattr(value, "_cache_size"):
        yield prefix, value
    elif isinstance(value, (tuple, list)):
        for i, item in enumerate(value):
            yield from _iter_jitted(item, f"{prefix}[{i}]")
    elif isinstance(value, dict):
        for k, item in value.items():
            yield from _iter_jitted(item, f"{prefix}[{k!r}]")


def cache_compile_counts(fns: Dict[Any, Any]) -> Dict[str, int]:
    """``{cache-key label: compile count}`` for a ``_fns``-style dict."""
    out = {}
    for key, value in fns.items():
        for label, fn in _iter_jitted(value, str(key)):
            out[label] = int(fn._cache_size())
    return out


class CompileCacheLint:
    """Wraps an engine/executor compile cache and asserts the one-compile-
    per-key property across a workload.

    Usage::

        lint = CompileCacheLint(engine._fns, target="serve-engine")
        ...run warmup workload (every key compiles once)...
        lint.snapshot()
        ...repeat the same workload shapes...
        result = lint.findings()     # any growth/extra compile = error

    ``findings(max_per_key=1)`` alone (no snapshot) checks the absolute
    budget: no cached entry may ever have compiled more than once.
    """

    def __init__(self, fns: Dict[Any, Any], target: str = "compile-cache"):
        self._fns = fns
        self.target = target
        self._snap: Dict[str, int] = {}
        self._snapped = False

    def snapshot(self) -> Dict[str, int]:
        self._snap = cache_compile_counts(self._fns)
        self._snapped = True
        return dict(self._snap)

    def findings(self, max_per_key: int = 1) -> PassResult:
        counts = cache_compile_counts(self._fns)
        result = PassResult("retrace", self.target, checked=len(counts))
        if not counts:
            result.findings.append(Finding(
                "retrace", SEVERITY_WARNING, self.target,
                "compile cache is empty — retrace lint is vacuous here"))
            return result
        for label, count in counts.items():
            if count > max_per_key:
                result.findings.append(Finding(
                    "retrace", SEVERITY_ERROR, f"{self.target}/{label}",
                    f"cache key compiled {count}x (budget {max_per_key}) — "
                    "unexpected retrace (weak-type promotion, shape drift, "
                    "or non-hashable static arg)",
                    {"count": count, "budget": max_per_key}))
            baseline = self._snap.get(label)
            if baseline is None:
                if self._snapped and count > 0:
                    # drift usually mints a NEW cache key rather than
                    # retracing an old one (a drifted shape hashes to a
                    # different (slots, cap, chunk, ...) tuple) — a key born
                    # after the warmup snapshot is the same contract breach
                    result.findings.append(Finding(
                        "retrace", SEVERITY_ERROR, f"{self.target}/{label}",
                        f"NEW cache key compiled {count}x after the warmup "
                        "snapshot — the repeated workload was supposed to "
                        "hit existing keys (shape/key drift)",
                        {"count": count}))
            elif count > baseline and count <= max_per_key:
                # growth within the absolute budget (e.g. a warm key
                # recompiling under a budget of 2) — still a retrace
                result.findings.append(Finding(
                    "retrace", SEVERITY_ERROR, f"{self.target}/{label}",
                    f"cache key retraced after warmup ({baseline} -> {count} "
                    "compiles for repeated identical workload shapes)",
                    {"baseline": baseline, "count": count}))
        return result

    def assert_clean(self, max_per_key: int = 1) -> PassResult:
        result = self.findings(max_per_key=max_per_key)
        errors = [f for f in result.findings
                  if f.severity == SEVERITY_ERROR]
        if errors:
            raise RetraceError(errors)
        return result
