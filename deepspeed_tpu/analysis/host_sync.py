"""Hot-path host-sync detector: AST rule + trace-time runtime check.

A single stray ``float()`` / ``.item()`` / ``np.asarray()`` /
``block_until_ready()`` on a device value inside the decode-chunk or
train-step path stalls the async dispatch queue once per step — the
difference between a pipelined hot loop and one that serializes on the host.
Two complementary views:

- **AST half** (:class:`HostSyncRule`, :func:`hot_path_sync_findings`): scans
  the declared hot-path functions (:data:`HOT_PATH_SPECS`) for sync-shaped
  calls. Deliberate syncs are *annotated*, not silent: a
  ``# lint: host-sync-ok`` marker anywhere in the enclosing statement, or in
  the comment block immediately above it, downgrades the call to an ``info``
  finding (it stays visible in the report) — the statement is the annotation
  unit, so a multi-line harvest tuple needs one marker, not one per line.
  The documented cases: the executor's TTFT-honesty syncs and
  chunk-boundary harvest, and the training engine's monitor-gated
  ``Train/*`` event build.
- **runtime half** (:func:`trace_sync_findings`): traces the function under
  ``jax.transfer_guard("disallow")`` — a concretization
  (``.item()``/``float()`` on a tracer) or an implicit device transfer
  during trace becomes a finding instead of a silent per-dispatch stall.
"""

import ast
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

from .report import Finding, PassResult, SEVERITY_ERROR, SEVERITY_INFO

#: marker comment that declares a deliberate, documented host sync
ALLOW_MARKER = "lint: host-sync-ok"

#: attribute-call names that force a device->host sync
_SYNC_ATTRS = {"block_until_ready", "item", "copy_to_host_async", "numpy",
               "tolist"}
#: ``np.<name>(...)`` calls that materialize a device array on host
_NP_FUNCS = {"asarray", "array"}
#: builtins that concretize a device scalar (``int()`` is deliberately NOT
#: banned: hot paths legitimately wrap host ints everywhere, and a device
#: value reaching ``int()`` almost always reaches ``np.asarray``/``float``
#: first — the signal stays, the noise goes)
_SYNC_BUILTINS = {"float", "bool"}


@dataclass
class HotPathSpec:
    """One file's hot-path anchors: functions (``name`` or ``Class.method``)
    whose bodies — including every nested closure — must not host-sync
    unannotated."""
    path: str                       # repo-relative
    anchors: Tuple[str, ...]
    #: extra allowed builtin names for this spec (e.g. a file whose hot path
    #: legitimately wraps python ints)
    allow_builtins: Tuple[str, ...] = ()


#: THE declared hot paths. decode_fns builders are fully traced (zero syncs
#: expected); the executor and train_batch are host drivers whose deliberate
#: boundary syncs carry the ALLOW_MARKER annotation.
HOT_PATH_SPECS: Tuple[HotPathSpec, ...] = (
    HotPathSpec("deepspeed_tpu/inference/decode_fns.py",
                ("build_prefill", "build_prefix_prefill",
                 "build_decode_loop", "build_decode_chunk",
                 "build_paged_decode_chunk")),
    HotPathSpec("deepspeed_tpu/inference/serving/executor.py",
                ("ChunkedDecodeExecutor._chunk_fn",
                 "ChunkedDecodeExecutor._prefill_fn",
                 "ChunkedDecodeExecutor._suffix_prefill_fn",
                 "ChunkedDecodeExecutor._suffix_prefill_fn_paged",
                 "ChunkedDecodeExecutor.prefill_into_slot",
                 "ChunkedDecodeExecutor.run_chunk")),
    HotPathSpec("deepspeed_tpu/runtime/engine.py",
                ("DeepSpeedEngine._build_train_step",
                 "DeepSpeedEngine._build_train_step_quantized",
                 "DeepSpeedEngine.train_batch",
                 "DeepSpeedEngine._write_monitor_events")),
)


def _sync_call_name(node: ast.Call, allow_builtins) -> Optional[str]:
    """The banned-call label a Call node matches, or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in _SYNC_ATTRS:
            return f".{fn.attr}()"
        if fn.attr in _NP_FUNCS and isinstance(fn.value, ast.Name) \
                and fn.value.id in ("np", "numpy", "onp"):
            return f"{fn.value.id}.{fn.attr}()"
    elif isinstance(fn, ast.Name):
        if fn.id in _SYNC_BUILTINS and fn.id not in allow_builtins:
            # float()/int() over a literal or pure-host expression is noise;
            # only constant args are provably host-only at the AST level
            if not all(isinstance(a, ast.Constant) for a in node.args):
                return f"{fn.id}()"
    return None


def _anchor_functions(tree: ast.Module, anchors: Sequence[str]):
    """Yield ``(qualname, FunctionDef)`` for each anchor present in the
    module (top-level functions and single-level ``Class.method``)."""
    wanted = set(anchors)
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in wanted:
            yield node.name, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{node.name}.{sub.name}"
                    if qual in wanted:
                        yield qual, sub


def _stmt_span(fn: ast.AST, lineno: int) -> Tuple[int, int]:
    """Line span of the innermost statement containing ``lineno`` (the
    annotation unit: a multi-line statement is annotated as a whole)."""
    best = None
    for node in ast.walk(fn):
        if isinstance(node, ast.stmt) and node.lineno <= lineno \
                <= (node.end_lineno or node.lineno):
            if best is None or node.lineno >= best[0]:
                best = (node.lineno, node.end_lineno or node.lineno)
    return best or (lineno, lineno)


def _annotated(source_lines: List[str], fn: ast.AST, lineno: int) -> bool:
    """True when the enclosing statement — any of its lines, or the
    contiguous comment block immediately above it — carries the allow
    marker."""
    start, end = _stmt_span(fn, lineno)
    for ln in range(start, min(end, len(source_lines)) + 1):
        if ALLOW_MARKER in source_lines[ln - 1]:
            return True
    ln = start - 1
    while ln >= 1 and source_lines[ln - 1].lstrip().startswith("#"):
        if ALLOW_MARKER in source_lines[ln - 1]:
            return True
        ln -= 1
    return False


def _spec_findings(spec: HotPathSpec, tree: ast.Module,
                   source_lines: List[str]) -> Tuple[List[Finding], int]:
    """Scan one parsed file against one spec; returns ``(findings,
    n_anchors_checked)``."""
    findings: List[Finding] = []
    anchors = dict(_anchor_functions(tree, spec.anchors))
    for missing in set(spec.anchors) - set(anchors):
        findings.append(Finding(
            "host_sync", SEVERITY_ERROR, f"{spec.path}:{missing}",
            f"declared hot-path anchor {missing!r} no longer exists — "
            "update analysis.host_sync.HOT_PATH_SPECS"))
    for qual, fn in anchors.items():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            label = _sync_call_name(node, spec.allow_builtins)
            if label is None:
                continue
            site = f"{spec.path}:{node.lineno} ({qual})"
            if _annotated(source_lines, fn, node.lineno):
                findings.append(Finding(
                    "host_sync", SEVERITY_INFO, site,
                    f"annotated host sync {label} (documented exception)",
                    {"call": label, "qualname": qual}))
            else:
                findings.append(Finding(
                    "host_sync", SEVERITY_ERROR, site,
                    f"host sync {label} on the hot path — stalls the "
                    "async dispatch queue every step; hoist it out or "
                    f"annotate the line with '# {ALLOW_MARKER} (why)'",
                    {"call": label, "qualname": qual}))
    return findings, len(anchors)


def hot_path_sync_findings(repo_root: str,
                           specs: Sequence[HotPathSpec] = HOT_PATH_SPECS
                           ) -> PassResult:
    """Run the AST half over every declared hot path (missing anchors are
    errors — this entry must run even when the files are unchanged, so spec
    rot is caught)."""
    import os
    result = PassResult("host_sync", "hot-paths", checked=0)
    for spec in specs:
        path = os.path.join(repo_root, spec.path)
        with open(path) as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        findings, n_anchors = _spec_findings(spec, tree, source.splitlines())
        result.findings.extend(findings)
        result.checked += n_anchors
    return result


class HostSyncRule:
    """The same check as an ``AstRule`` for :func:`run_ast_rules` — files
    outside the declared specs contribute nothing. Note the spec-driven
    entry (:func:`hot_path_sync_findings`) is still what the full sweep
    runs: a rule sweep restricted to changed files would never notice a
    spec whose file was deleted."""

    name = "host_sync"

    def __init__(self, specs: Sequence[HotPathSpec] = HOT_PATH_SPECS):
        self.specs = specs

    def check(self, tree: ast.Module, source_lines: List[str],
              relpath: str) -> List[Finding]:
        findings: List[Finding] = []
        for spec in self.specs:
            if spec.path == relpath:
                findings.extend(_spec_findings(spec, tree, source_lines)[0])
        return findings


def trace_sync_findings(fn: Callable, args: Tuple[Any, ...],
                        target: str = "trace") -> PassResult:
    """Runtime half: trace ``fn(*args)`` under a transfer guard.

    A host sync written against a *traced* value concretizes — ``.item()`` /
    ``float()`` raise ``ConcretizationTypeError``, ``np.asarray()`` raises
    ``TracerArrayConversionError``, ``bool()`` its boolean sibling — so the
    injected-sync-in-a-chunk-body regression is caught deterministically at
    trace time, before it ever ships a per-dispatch stall. The transfer
    guard is belt-and-braces on top: any *implicit* device transfer the
    trace performs (a fresh host constant pushed per-dispatch) also fails.
    """
    import jax
    tracer_errors = tuple(
        e for e in (getattr(jax.errors, n, None)
                    for n in ("ConcretizationTypeError",
                              "TracerArrayConversionError",
                              "TracerBoolConversionError",
                              "TracerIntegerConversionError"))
        if e is not None)
    result = PassResult("host_sync_trace", target, checked=1)
    try:
        with jax.transfer_guard("disallow"):
            jax.make_jaxpr(fn)(*args)
    except tracer_errors as e:
        result.findings.append(Finding(
            "host_sync_trace", SEVERITY_ERROR, target,
            "traced value concretized during trace (float()/.item()/"
            "np.asarray() on a tracer) — this would host-sync every dispatch",
            {"error": str(e).splitlines()[0]}))
    except Exception as e:  # transfer guard violations are XlaRuntimeError
        # only the guard's own message shape is a finding ("Disallowed
        # host-to-device transfer ..."); any other exception — even one that
        # happens to mention "transfer" — is a real trace failure and must
        # propagate with its traceback, not be re-diagnosed
        msg = str(e)
        if "Disallowed" not in msg or "transfer" not in msg.lower():
            raise
        result.findings.append(Finding(
            "host_sync_trace", SEVERITY_ERROR, target,
            "implicit device transfer during trace (host constant pushed "
            "per-dispatch)", {"error": msg.splitlines()[0][:200]}))
    return result
