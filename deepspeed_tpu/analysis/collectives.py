"""Collective-schema pass: bytes-on-wire accounted from the jaxpr itself.

``CollectiveSpans`` (``utils/comms_logging.py``) records each decomposed
collective call site's modeled wire volume at trace time — but the recording
is hand-written per site, which is exactly how the PR 3 "last-call overwrite"
undercount happened (n_layer traces at one site overwrote instead of
summing). This pass closes the loop: it walks the traced program's jaxpr,
statically accounts bytes-on-wire for every *explicit* collective primitive
(``ppermute``/``all_gather``/``reduce_scatter``/``psum``/``all_to_all`` —
shapes x dtype x ring factor), and cross-checks the total against what the
spans recorded during the same trace. A site that under- or over-records by
any margin fails the pass, forever.

Accounting convention (per-worker bytes, ring algorithms — the same
convention ``parallel/overlap.py`` records):

==================  ====================================================
primitive           wire bytes per worker
==================  ====================================================
ppermute            operand nbytes (each worker forwards its buffer once)
all_gather          (W - 1) x operand (per-shard) nbytes
reduce_scatter      (W - 1) x output (per-shard) nbytes
psum                2 (W - 1) / W x operand nbytes (ring allreduce)
all_to_all          (W - 1) / W x operand nbytes
==================  ====================================================

GSPMD-*implicit* collectives (a ``with_sharding_constraint`` that lowers to
an a2a, the monolithic-psum fallback's allreduce) never appear in the jaxpr
— sites recorded with those ops are excluded from the exact cross-check and
surfaced as ``info`` findings instead (documented limitation; their volume
is checked by the bench A/B lanes, not statically).

Quantized wires need no special convention: the ppermute rule sums ALL
operand avals, so a fused-quantized-ring hop (``parallel/qring.py``) —
one intN carrier (int4 packs two elements per int8 byte, so the aval IS the
wire footprint) plus one fp32 scale vector per block — is accounted from
shapes x dtypes exactly like any fp hop. :func:`qring_wire_bytes` is the
closed form of that int-chunk arithmetic; the qring lint lane asserts the
recorded span, this closed form, and the jaxpr sum agree to the byte.
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .jaxpr_passes import subjaxprs
from .report import Finding, PassResult, SEVERITY_ERROR, SEVERITY_INFO

#: collective primitives with static wire accounting
COLLECTIVE_PRIMS = ("ppermute", "all_gather", "reduce_scatter", "psum",
                    "all_to_all")

#: span ops that are GSPMD-implicit (absent from the jaxpr)
IMPLICIT_SPAN_OPS = ("all_reduce",)


def _aval_nbytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def _axes_size(axis_names, axis_env: Dict[str, int]) -> Optional[int]:
    names = axis_names if isinstance(axis_names, (tuple, list)) \
        else (axis_names,)
    size = 1
    for name in names:
        if name not in axis_env:
            return None
        size *= axis_env[name]
    return size


def _eqn_wire_bytes(eqn, axis_env: Dict[str, int]) -> Optional[int]:
    """Per-worker wire bytes for one collective eqn; None when the axis size
    is unknown (collective outside any recorded mesh context)."""
    name = eqn.primitive.name
    in_bytes = sum(_aval_nbytes(v.aval) for v in eqn.invars
                   if hasattr(v, "aval"))
    out_bytes = sum(_aval_nbytes(v.aval) for v in eqn.outvars)
    if name == "ppermute":
        return in_bytes
    if name == "all_gather":
        W = eqn.params.get("axis_size") or _axes_size(
            eqn.params.get("axis_name", ()), axis_env)
        return None if W is None else (W - 1) * in_bytes
    if name == "reduce_scatter":
        W = eqn.params.get("axis_size") or _axes_size(
            eqn.params.get("axis_name", ()), axis_env)
        return None if W is None else (W - 1) * out_bytes
    if name == "psum":
        W = _axes_size(eqn.params.get("axes", ()), axis_env)
        return None if W is None else int(2 * (W - 1) * in_bytes / W)
    if name == "all_to_all":
        W = _axes_size(eqn.params.get("axis_name", ()), axis_env)
        return None if W is None else int((W - 1) * in_bytes / W)
    return None


def collective_accounting(fn_or_jaxpr, args=()) -> List[Dict[str, Any]]:
    """Every explicit collective in the program, with modeled wire bytes.

    Returns records ``{"primitive", "wire_bytes", "axis_env", "shape"}`` in
    program order; ``wire_bytes`` is None when the enclosing axis size could
    not be resolved (reported by the cross-check as an error — an unaccounted
    collective is exactly what the pass exists to catch).
    """
    import jax
    if hasattr(fn_or_jaxpr, "eqns"):
        jaxpr = fn_or_jaxpr
    elif hasattr(fn_or_jaxpr, "jaxpr"):
        jaxpr = fn_or_jaxpr.jaxpr
    else:
        jaxpr = jax.make_jaxpr(fn_or_jaxpr)(*args).jaxpr
    records: List[Dict[str, Any]] = []

    def walk(jx, axis_env: Dict[str, int]):
        for eqn in jx.eqns:
            sub_env = axis_env
            mesh = eqn.params.get("mesh")
            if mesh is not None and hasattr(mesh, "shape"):
                sub_env = dict(axis_env)
                sub_env.update(dict(mesh.shape))
            if eqn.primitive.name in COLLECTIVE_PRIMS:
                shapes = [tuple(getattr(v.aval, "shape", ()))
                          for v in eqn.invars if hasattr(v, "aval")]
                records.append({
                    "primitive": eqn.primitive.name,
                    "wire_bytes": _eqn_wire_bytes(eqn, axis_env),
                    "axis_env": dict(axis_env),
                    "shape": shapes[0] if shapes else (),
                })
            for sub in subjaxprs(eqn):
                walk(sub, sub_env)

    walk(jaxpr, {})
    return records


def qring_wire_bytes(m: int, n: int, W: int, *, wire_bits: Optional[int] = 8,
                     block: int = 256, bidirectional: bool = True) -> int:
    """Closed-form per-worker bytes-on-wire of ONE fused quantized
    matmul-reduce-scatter dispatch (``parallel/qring.py``) — the intN-chunk
    wire arithmetic under this pass's ppermute convention.

    ``m``: padded flattened local token count (rows entering the ring; must
    divide by ``W``); ``n``: output features. Each serial step ppermutes one
    ``(m/W, n_dir)`` accumulator chunk as an intN carrier + one fp32 scale
    per ``block`` elements over the block-padded flat length
    (``comm.compressed.intn_wire_nbytes``); bidirectional rings make
    ``2 (W-1)`` half-width hops, unidirectional ``W-1`` full-width ones.
    ``wire_bits=None`` models the fp32 wire (the ground-truth lane).

    The qring span records this same number at trace time and the jaxpr
    side re-derives it from the ppermute operand avals — three independent
    computations that the lint lane and ``bench.py --qring`` require to
    agree exactly, so bytes-on-wire claims are never hand-computed.
    """
    from ..comm.compressed import intn_wire_nbytes
    m_blk = m // W
    bidir = bidirectional and n % 2 == 0
    n_dir = n // 2 if bidir else n
    hop = (m_blk * n_dir * 4 if wire_bits is None
           else intn_wire_nbytes(m_blk * n_dir, block, wire_bits))
    return (W - 1) * (2 if bidir else 1) * hop


def _span_delta(before: Dict[str, Dict], after: Dict[str, Dict]
                ) -> Dict[str, Dict]:
    """Per-site recorded-bytes delta between two ``CollectiveSpans.summary()``
    snapshots (``bytes_total`` accumulates across traces)."""
    delta = {}
    for site, rec in after.items():
        prev = before.get(site, {}).get("bytes_total", 0)
        d = rec["bytes_total"] - prev
        if d or site not in before:
            delta[site] = dict(rec, bytes_total=d)
    return delta


def crosscheck_findings(fn, args, *, spans=None,
                        site_prefixes: Optional[Sequence[str]] = None,
                        target: str = "collectives") -> PassResult:
    """Trace ``fn(*args)``; assert jaxpr-accounted wire bytes == span-recorded
    wire bytes for the explicit-collective sites touched by the trace.

    ``spans``: the :class:`~deepspeed_tpu.utils.comms_logging.CollectiveSpans`
    instance the traced sites record into (defaults to the process-global
    one). ``site_prefixes`` names the sites the caller EXPECTS the trace to
    record — it shapes the report, not the arithmetic: the byte equation is
    always program-wide (the jaxpr side cannot be filtered by site, so a
    filtered recorded-side would manufacture false mismatches), and any
    explicit-op site recorded OUTSIDE the expected prefixes is surfaced as
    its own ``info`` finding.
    """
    import jax
    from ..utils.comms_logging import collective_spans
    spans = spans if spans is not None else collective_spans
    before = spans.summary()
    closed = jax.make_jaxpr(fn)(*args)
    delta = _span_delta(before, spans.summary())

    records = collective_accounting(closed)
    result = PassResult("collective_schema", target, checked=len(records))

    unaccounted = [r for r in records if r["wire_bytes"] is None]
    for r in unaccounted:
        result.findings.append(Finding(
            "collective_schema", SEVERITY_ERROR, target,
            f"collective {r['primitive']} over {r['shape']} has no "
            "resolvable axis size — unaccounted wire traffic",
            {"primitive": r["primitive"]}))

    implicit = {s: r for s, r in delta.items()
                if r.get("op") in IMPLICIT_SPAN_OPS}
    for s, r in implicit.items():
        result.findings.append(Finding(
            "collective_schema", SEVERITY_INFO, f"{target}/{s}",
            f"site records GSPMD-implicit op {r['op']!r} "
            f"({r['bytes_total']} bytes) — not statically checkable from "
            "the jaxpr; covered by bench A/B lanes",
            {"op": r["op"], "bytes": r["bytes_total"]}))

    if site_prefixes is not None:
        unexpected = [s for s in delta
                      if s not in implicit
                      and not any(s.startswith(p) for p in site_prefixes)]
        for s in unexpected:
            result.findings.append(Finding(
                "collective_schema", SEVERITY_INFO, f"{target}/{s}",
                f"trace also recorded site {s!r} outside the expected "
                f"prefixes {tuple(site_prefixes)} — its bytes participate "
                "in the program-wide cross-check below",
                {"bytes": delta[s]["bytes_total"]}))

    modeled = sum(r["wire_bytes"] for r in records
                  if r["wire_bytes"] is not None)
    recorded = sum(r["bytes_total"] for s, r in delta.items()
                   if s not in implicit)
    if modeled != recorded:
        result.findings.append(Finding(
            "collective_schema", SEVERITY_ERROR, target,
            f"bytes-on-wire mismatch: jaxpr accounts {modeled} but "
            f"CollectiveSpans recorded {recorded} for sites "
            f"{sorted(s for s in delta if s not in implicit)} — a call site "
            "under/over-records (the PR 3 last-call-overwrite class)",
            {"modeled": int(modeled), "recorded": int(recorded),
             "sites": {s: int(r["bytes_total"]) for s, r in delta.items()
                       if s not in implicit}}))
    return result
