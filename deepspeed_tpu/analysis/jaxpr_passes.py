"""Jaxpr structural passes: sub-jaxpr walking and the loop-invariance pin.

:func:`assert_loop_invariant` is the generalized form of the PR 5 dequant-hoist
check that used to live as a bespoke walk inside ``test_weight_quant.py``: it
structurally pins values/ops OUT of compiled loop bodies (``while`` — dynamic
``fori_loop``/``while_loop`` — and ``scan``, which static-bound ``fori_loop``
lowers to). The jaxpr view is the one that matters: XLA's own LICM may hoist a
regression in the final HLO on one backend version and not the next, so "the
optimized HLO happened to be clean" is not a contract — "our trace never put
it in the body" is.

Predicates:

- ``invar_predicate(aval)`` — flags loop-body *inputs* (while/scan bodies
  receive loop constants as invars, so "int8 entered the body" means the
  quantized payload is consumed per-step instead of once per dispatch);
- ``eqn_predicate(eqn)`` — flags *operations* traced inside a body (e.g.
  ``lambda e: e.primitive.name == "custom_jvp_call"``).
"""

from typing import Any, Callable, Iterator, List, Optional, Tuple

import jax

from .report import Finding, SEVERITY_ERROR

#: primitives whose sub-jaxprs execute once per loop iteration
LOOP_PRIMITIVES = ("while", "scan")


def subjaxprs(eqn) -> Iterator[Any]:
    """Every inner ``Jaxpr`` reachable from one equation's params (closed
    jaxprs are unwrapped; lists/tuples of jaxprs — e.g. ``cond`` branches —
    are walked)."""
    for param in eqn.params.values():
        items = param if isinstance(param, (list, tuple)) else [param]
        for item in items:
            # ClosedJaxpr first: it forwards .eqns, so the order matters
            if hasattr(item, "jaxpr"):         # ClosedJaxpr (while/scan/pjit)
                yield item.jaxpr
            elif hasattr(item, "eqns"):        # plain Jaxpr (e.g. shard_map)
                yield item


def _as_jaxpr(fn_or_jaxpr, args) -> Any:
    if hasattr(fn_or_jaxpr, "eqns"):
        return fn_or_jaxpr
    if hasattr(fn_or_jaxpr, "jaxpr"):
        return fn_or_jaxpr.jaxpr
    return jax.make_jaxpr(fn_or_jaxpr)(*args).jaxpr


class LoopInvarianceError(AssertionError):
    """A value/op the contract pins loop-invariant was traced inside a loop
    body (e.g. dequant re-derived every decode step)."""

    def __init__(self, what: str, violations: List[str]):
        self.what = what
        self.violations = list(violations)
        detail = "; ".join(violations[:8])
        if len(violations) > 8:
            detail += f"; ... ({len(violations) - 8} more)"
        super().__init__(
            f"loop-invariance contract {what!r} violated inside compiled "
            f"loop bodies: {detail}")


def loop_body_findings(fn_or_jaxpr, args=(), *,
                       invar_predicate: Optional[Callable[[Any], bool]] = None,
                       eqn_predicate: Optional[Callable[[Any], bool]] = None,
                       what: str = "loop-invariant",
                       site: str = "jaxpr") -> Tuple[List[Finding], int]:
    """Walk the program's jaxpr; flag predicate matches inside any loop body.

    Returns ``(findings, n_loop_bodies_inspected)`` — callers can assert the
    walk actually saw a loop (a refactor that removes the loop entirely would
    otherwise pass vacuously).
    """
    if invar_predicate is None and eqn_predicate is None:
        raise ValueError("need invar_predicate and/or eqn_predicate")
    jaxpr = _as_jaxpr(fn_or_jaxpr, args)
    findings: List[Finding] = []
    seen_bodies = [0]

    def walk(jx, inside: bool, path: str):
        if inside:
            if invar_predicate is not None:
                for v in jx.invars:
                    aval = getattr(v, "aval", None)
                    if aval is not None and invar_predicate(aval):
                        findings.append(Finding(
                            "loop_invariance", SEVERITY_ERROR, site,
                            f"{what}: loop-body input {aval} at {path}",
                            {"aval": str(aval), "loop_path": path}))
            if eqn_predicate is not None:
                for eqn in jx.eqns:
                    if eqn_predicate(eqn):
                        findings.append(Finding(
                            "loop_invariance", SEVERITY_ERROR, site,
                            f"{what}: op {eqn.primitive.name} traced inside "
                            f"loop body at {path}",
                            {"primitive": eqn.primitive.name,
                             "loop_path": path}))
        for eqn in jx.eqns:
            is_loop = eqn.primitive.name in LOOP_PRIMITIVES
            if is_loop and not inside:
                seen_bodies[0] += 1
            sub_path = (f"{path}/{eqn.primitive.name}"
                        if is_loop else path)
            for sub in subjaxprs(eqn):
                walk(sub, inside or is_loop, sub_path)

    walk(jaxpr, False, site)
    return findings, seen_bodies[0]


def assert_loop_invariant(fn_or_jaxpr, args=(), *,
                          invar_predicate=None, eqn_predicate=None,
                          what: str = "loop-invariant",
                          require_loop: bool = True) -> int:
    """Raise :class:`LoopInvarianceError` if the predicate matches inside any
    compiled loop body; returns the number of loop bodies inspected.

    ``require_loop=True`` (default) also raises if the program contains NO
    loop at all — the pin must fail loudly when the loop it guards is
    refactored away, not silently pass on an empty walk.
    """
    findings, n_loops = loop_body_findings(
        fn_or_jaxpr, args, invar_predicate=invar_predicate,
        eqn_predicate=eqn_predicate, what=what)
    if require_loop and n_loops == 0:
        raise LoopInvarianceError(what, ["program contains no while/scan "
                                         "loop — pin target vanished"])
    if findings:
        raise LoopInvarianceError(what, [f.message for f in findings])
    return n_loops
