r"""Donation audit: every ``donate_argnums`` buffer is actually donated.

``jax.jit(..., donate_argnums=...)`` is a *request*: if XLA cannot alias a
donated input to an output (dtype/layout mismatch, output doesn't exist, an
engine rebinding handed the jit a buffer tree whose structure drifted), it
silently falls back to a copy — the donated-HBM saving evaporates and, worse,
callers that rebind "the donated pool" may keep OLD buffers alive (the exact
bug class the PR 8/9 watchdog/restore seams guard by hand). This pass reads
the contract off the compiled executable: the ``input_output_alias`` table of
the optimized HLO must cover every donated (and kept) parameter.

Deliberate non-donation is declared, not silent: pass ``allow=`` patterns
matched against the flat arg-leaf path (substring by default, e.g.
``"caches"`` or ``"[2]"`` for the third positional arg; prefix with ``re:``
for a regex, e.g. ``r"re:^\[2\]"``) and the pass downgrades those leaves to
``info`` findings that name the allowlist entry — visible in the report,
not failing it.
"""

import re
from typing import Any, List, Optional, Sequence, Tuple

import jax

from .report import (Finding, PassResult, SEVERITY_ERROR, SEVERITY_INFO,
                     SEVERITY_WARNING)


class DonationError(AssertionError):
    """A donated buffer was not aliased into any output (silent-copy
    fallback)."""

    def __init__(self, findings: List[Finding]):
        self.findings = list(findings)
        super().__init__("donation contract violated: " +
                         "; ".join(f.message for f in findings[:6]))


def _alias_param_positions(compiled_text: str) -> Optional[set]:
    """Parameter positions appearing as alias sources in the executable's
    ``input_output_alias`` table; None when no table exists at all."""
    m = re.search(r"input_output_alias=\{", compiled_text)
    if m is None:
        return None
    # scan to the matching close brace (entries nest one brace level deep)
    depth, i = 1, m.end()
    while i < len(compiled_text) and depth:
        depth += {"{": 1, "}": -1}.get(compiled_text[i], 0)
        i += 1
    body = compiled_text[m.end():i - 1]
    # entries look like `{0}: (2, {}, may-alias)` — capture the param index
    return {int(p) for p in re.findall(r":\s*\((\d+)", body)}


def _info_aval(info) -> Any:
    # jax 0.4.x spells it ArgInfo._aval; newer versions may expose .aval
    return getattr(info, "aval", None) or getattr(info, "_aval", None)


def _flat_args_info(lowered) -> List[Tuple[str, Any]]:
    """``(path, ArgInfo)`` per flattened argument leaf, in parameter order."""
    is_info = lambda x: hasattr(x, "donated")  # noqa: E731
    leaves = jax.tree_util.tree_flatten_with_path(
        lowered.args_info, is_leaf=is_info)[0]
    return [(jax.tree_util.keystr(path), info) for path, info in leaves]


def _allowed(path: str, allow: Sequence[str]) -> Optional[str]:
    for pat in allow:
        # plain patterns are SUBSTRINGS (arg paths are full of brackets — a
        # bracketed substring like "[2]" must never silently become a regex
        # character class matching the wrong leaves); regex matching is
        # explicit via an "re:" prefix
        if pat.startswith("re:"):
            if re.search(pat[3:], path):
                return pat
        elif pat in path:
            return pat
    return None


def donation_findings(fn, args, kwargs=None, *, donate_argnums=None,
                      allow: Sequence[str] = (),
                      target: str = "donation") -> PassResult:
    """Audit one program's donation contract.

    ``fn`` is either an already-``jax.jit``-ed callable (donation baked in —
    e.g. an entry of an engine's ``_fns`` cache) or a plain function with
    ``donate_argnums`` given here. ``args``/``kwargs`` are representative
    abstract-or-concrete arguments (only shapes/dtypes matter; this lowers,
    it does not execute).
    """
    kwargs = kwargs or {}
    if donate_argnums is not None:
        fn = jax.jit(fn, donate_argnums=donate_argnums)
    if not hasattr(fn, "lower"):
        raise TypeError("fn must be jax.jit-wrapped (or pass donate_argnums "
                        "so the pass can wrap it)")
    lowered = fn.lower(*args, **kwargs)
    compiled = lowered.compile()
    flat = _flat_args_info(lowered)
    donated_idx = [i for i, (_, info) in enumerate(flat) if info.donated]
    result = PassResult("donation", target, checked=len(donated_idx))
    if not donated_idx:
        result.findings.append(Finding(
            "donation", SEVERITY_WARNING, target,
            "program donates nothing — donation audit is vacuous here"))
        return result

    # flat arg index -> executable parameter position (unused args dropped)
    kept = getattr(getattr(compiled, "_executable", None),
                   "_kept_var_idx", None)
    kept = sorted(kept) if kept is not None else list(range(len(flat)))
    param_pos = {flat_i: pos for pos, flat_i in enumerate(kept)}

    aliased = _alias_param_positions(compiled.as_text())
    for i in donated_idx:
        path, info = flat[i]
        site = f"{target}{path}"
        if i not in param_pos:
            result.findings.append(Finding(
                "donation", SEVERITY_WARNING, site,
                f"donated argument {path} is unused by the computation "
                "(dropped from the executable — nothing to alias)",
                {"aval": str(_info_aval(info))}))
            continue
        if aliased is not None and param_pos[i] in aliased:
            continue
        pat = _allowed(path, allow)
        if pat is not None:
            result.findings.append(Finding(
                "donation", SEVERITY_INFO, site,
                f"donated argument {path} not aliased — allowlisted "
                f"by {pat!r}", {"aval": str(_info_aval(info)), "allow": pat}))
            continue
        result.findings.append(Finding(
            "donation", SEVERITY_ERROR, site,
            f"donated argument {path} ({_info_aval(info)}) is NOT aliased "
            "in the compiled executable — silent copy fallback; the caller "
            "believes this buffer was consumed",
            {"aval": str(_info_aval(info))}))
    return result


def assert_all_donated(fn, args, kwargs=None, *, donate_argnums=None,
                       allow: Sequence[str] = (), target: str = "donation"):
    """Raise :class:`DonationError` unless every donated (kept) buffer is
    aliased; returns the :class:`~.report.PassResult` when clean."""
    result = donation_findings(fn, args, kwargs, donate_argnums=donate_argnums,
                               allow=allow, target=target)
    errors = [f for f in result.findings if f.severity == SEVERITY_ERROR]
    if errors:
        raise DonationError(errors)
    return result
