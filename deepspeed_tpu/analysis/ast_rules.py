"""AST rule runner: Python-level lint rules over the library source tree.

One framework for every source-level rule — the bare-``assert`` ban, the
metric-tag schema lint that used to be a private walker inside
``observability/schema.py``, and the hot-path host-sync rule
(:mod:`.host_sync`). Rules are objects with ``name`` and
``check(tree, source_lines, relpath) -> [Finding]``; :func:`run_ast_rules`
walks a file set once, parses each file once, and feeds every rule — so
adding a contract to a future PR is one rule class, not one bespoke walker.

Rule catalog:

- :class:`BareAssertRule` — no bare ``assert`` in library (non-test) code:
  asserts vanish under ``python -O``, so a guard written as one is a guard
  that does not exist in optimized deployments (the exact bug class PR 3
  fixed in ``chunked_matmul_reduce_scatter``). Tests keep their asserts
  (pytest rewrites them); library code raises explicit exceptions.
- :class:`EmissionTagRule` — every metric-tag literal that feeds an emission
  site resolves against the declared schema (``observability.schema.TAGS``).
"""

import ast
import fnmatch
import os
import re
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

from .report import Finding, PassResult, SEVERITY_ERROR


class AstRule:
    """Base: subclasses set ``name`` and implement :meth:`check`."""

    name = "ast-rule"

    def check(self, tree: ast.Module, source_lines: List[str],
              relpath: str) -> List[Finding]:
        raise NotImplementedError


# --------------------------------------------------------------- bare assert
class BareAssertRule(AstRule):
    """Ban ``assert`` statements in library code paths."""

    name = "bare_assert"

    def check(self, tree, source_lines, relpath):
        findings = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Assert):
                findings.append(Finding(
                    self.name, SEVERITY_ERROR, f"{relpath}:{node.lineno}",
                    "bare assert in library code — vanishes under python -O; "
                    "raise an explicit exception instead",
                    {"line": node.lineno}))
        return findings


# ------------------------------------------------------------- emission tags
_EMIT_FUNCS = {"write_events", "record_events", "record", "emit", "_write",
               "counter", "gauge", "histogram"}
_TAG_RE = re.compile(r"^(serving|router|Train|inference|latency|flight"
                     r"|anomaly)/[A-Za-z0-9_{}*./]+$")


def _literal_tag(node: ast.AST) -> Optional[str]:
    """Render a Str/JoinedStr AST node to a tag literal (f-string
    interpolations become ``*``); None when it isn't tag-shaped."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value
    elif isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        text = "".join(parts)
    else:
        return None
    return text if _TAG_RE.match(text) else None


def iter_emission_tags_from_tree(tree: ast.Module
                                 ) -> Iterator[Tuple[str, int]]:
    """Yield ``(tag_literal, lineno)`` for every tag-shaped string constant
    inside a function that calls one of the emit surfaces (``write_events`` /
    ``record_events`` / registry ``record`` / ``counter``/``gauge``/
    ``histogram``). Docstrings are skipped; constants inside an f-string are
    fragments of the rendered pattern, never tags themselves."""

    def calls_emit(fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                fname = None
                if isinstance(node.func, ast.Attribute):
                    fname = node.func.attr
                elif isinstance(node.func, ast.Name):
                    fname = node.func.id
                if fname in _EMIT_FUNCS:
                    return True
        return False

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not calls_emit(fn):
            continue
        body = fn.body
        # skip the docstring: prose mentions of tags are not emission sites
        if (body and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)):
            body = body[1:]
        for stmt in body:
            fragment_ids = set()
            for node in ast.walk(stmt):
                if isinstance(node, ast.JoinedStr):
                    for sub in ast.walk(node):
                        if sub is not node:
                            fragment_ids.add(id(sub))
            for node in ast.walk(stmt):
                if id(node) in fragment_ids:
                    continue
                tag = _literal_tag(node)
                if tag is not None:
                    yield tag, node.lineno


def iter_emission_tags(path: str) -> Iterator[Tuple[str, int]]:
    """File-path face of :func:`iter_emission_tags_from_tree` (the API
    ``observability.schema`` re-exports)."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    yield from iter_emission_tags_from_tree(tree)


class EmissionTagRule(AstRule):
    """Every emitted metric tag resolves against the declared schema.

    ``resolve`` is injected (``observability.schema.resolve``) so this module
    stays import-cycle-free; ``modules`` restricts the rule to the declared
    emitter files (tag-shaped strings elsewhere — docs, tests — are not
    emission sites)."""

    name = "emission_tags"

    def __init__(self, resolve: Callable[[str], Optional[str]],
                 modules: Sequence[str]):
        self.resolve = resolve
        self.modules = tuple(modules)

    def check(self, tree, source_lines, relpath):
        if relpath not in self.modules:
            return []
        findings = []
        for tag, lineno in iter_emission_tags_from_tree(tree):
            if self.resolve(tag) is None:
                findings.append(Finding(
                    self.name, SEVERITY_ERROR, f"{relpath}:{lineno}",
                    f"metric tag {tag!r} is not declared in "
                    "observability.schema.TAGS — declare it (kind + help) "
                    "before emitting it", {"tag": tag}))
        return findings


# -------------------------------------------------------------------- runner
#: paths never linted (generated/vendored would go here)
DEFAULT_EXCLUDES = ("tests/*", "*/tests/*")


def library_files(repo_root: str, package: str = "deepspeed_tpu",
                  excludes: Sequence[str] = DEFAULT_EXCLUDES) -> List[str]:
    """Repo-relative paths of every library ``.py`` file under ``package``."""
    out = []
    base = os.path.join(repo_root, package)
    for dirpath, _, names in os.walk(base):
        for name in sorted(names):
            if not name.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, name), repo_root)
            rel = rel.replace(os.sep, "/")
            if any(fnmatch.fnmatch(rel, pat) for pat in excludes):
                continue
            out.append(rel)
    return sorted(out)


def run_ast_rules(repo_root: str, rules: Sequence[AstRule],
                  paths: Optional[Sequence[str]] = None) -> PassResult:
    """Parse each file once; feed every rule. ``paths`` (repo-relative)
    restricts the sweep — the ``--changed-only`` fast mode."""
    if paths is None:
        paths = library_files(repo_root)
    names = "+".join(r.name for r in rules) or "none"
    result = PassResult("ast_rules", names, checked=0)
    for rel in paths:
        full = os.path.join(repo_root, rel)
        if not os.path.exists(full) or not rel.endswith(".py"):
            continue
        with open(full) as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=full)
        except SyntaxError as e:
            result.findings.append(Finding(
                "ast_rules", SEVERITY_ERROR, f"{rel}:{e.lineno or 0}",
                f"syntax error during lint parse: {e.msg}"))
            continue
        result.checked += 1
        lines = source.splitlines()
        for rule in rules:
            result.findings.extend(rule.check(tree, lines, rel))
    return result
