"""Program-contract static analysis: jaxpr/HLO lint passes + AST rules.

The codebase carries hard structural invariants that used to live as one-off
test walks or tribal notes in CHANGES.md — donation discipline on the serving
hot path, the one-compile-per-key property of the executor caches, dequant
hoisted out of decode loop bodies, bytes-on-wire accounting that matches the
program. This package turns each into a reusable, declarative **contract
pass** over a traced function's jaxpr / optimized HLO (plus an AST rule
runner for Python-level rules), so every future kernel/serving PR lands
against machine-checked contracts.

Pass catalog (see ``docs/ANALYSIS.md``):

- :mod:`.donation` — every ``donate_argnums`` buffer is actually aliased in
  the compiled executable (no silent-copy fallback);
- :mod:`.retrace` — compile-cache lint: one compile per ``_fns`` key, no
  weak-type/shape-drift retraces;
- :mod:`.host_sync` — hot-path host-sync detector (AST + trace-time hybrid);
- :mod:`.jaxpr_passes` — :func:`assert_loop_invariant`, the generalized
  dequant-hoist pin: structurally keeps ops out of while/scan bodies;
- :mod:`.collectives` — bytes-on-wire accounting from the jaxpr, cross-checked
  against ``CollectiveSpans`` records;
- :mod:`.ast_rules` — AST rule runner (bare-assert ban, emission-tag schema,
  hot-path sync rule) shared with ``observability.schema``;
- :mod:`.sweep` — the ``bin/ds-tpu-lint`` whole-repo sweep over the canonical
  traces + AST rules, emitting a JSON report.
"""

from .ast_rules import (AstRule, BareAssertRule, EmissionTagRule,
                        iter_emission_tags, run_ast_rules)
from .collectives import collective_accounting, crosscheck_findings
from .donation import DonationError, assert_all_donated, donation_findings
from .host_sync import (HOT_PATH_SPECS, HostSyncRule, hot_path_sync_findings,
                        trace_sync_findings)
from .jaxpr_passes import (LoopInvarianceError, assert_loop_invariant,
                           loop_body_findings)
from .report import Finding, PassResult, Report
from .retrace import CompileCacheLint, RetraceError, cache_compile_counts

__all__ = [
    "AstRule", "BareAssertRule", "EmissionTagRule", "iter_emission_tags",
    "run_ast_rules", "collective_accounting", "crosscheck_findings",
    "DonationError", "assert_all_donated", "donation_findings",
    "HOT_PATH_SPECS", "HostSyncRule", "hot_path_sync_findings",
    "trace_sync_findings", "LoopInvarianceError", "assert_loop_invariant",
    "loop_body_findings", "Finding", "PassResult", "Report",
    "CompileCacheLint", "RetraceError", "cache_compile_counts",
]
