"""Launcher package: multi-node runner + per-node spawner (reference deepspeed/launcher)."""
from .runner import main as runner_main  # noqa: F401
