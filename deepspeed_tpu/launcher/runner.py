"""Multi-node job runner — the ``deepspeed`` CLI re-based onto TPU topology.

TPU-native analogue of reference ``deepspeed/launcher/runner.py`` (``main:380``,
``parse_resource_pool:156``, ``parse_inclusion_exclusion:215``): resolves the set of
participating hosts and worker counts, then starts the per-node spawner
(:mod:`.launch`) everywhere.

Three resolution modes:

- **local** (default, single node): spawn ``--num_procs`` workers on this machine with a
  localhost coordinator — the CPU/dev loop and the single-host multi-chip case.
- **ssh**: reference-style hostfile (``hostname slots=N`` lines) with ``--include`` /
  ``--exclude`` filters; one ssh session per node runs ``python -m
  deepspeed_tpu.launcher.launch`` with that node's rank (the reference's PDSH runner,
  without the pdsh dependency).
- **tpu-pod**: on a Cloud TPU pod slice the runtime already starts one worker per host and
  publishes identity env (``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES``); the runner turns
  those into the coordinator contract and *execs the script in place* — no spawning, matching
  how multi-host JAX jobs actually start on TPU.
"""

import argparse
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

from ..utils.logging import logger

DEFAULT_MASTER_PORT = 29500
# env prefixes exported to remote nodes (reference runner.py EXPORT_ENVS)
EXPORT_ENV_PREFIXES = ("JAX_", "XLA_", "TPU_", "DS_TPU_", "LIBTPU_", "PYTHON")


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        prog="deepspeed_tpu",
        description="deepspeed_tpu launcher: run a training script across hosts/chips")
    parser.add_argument("-H", "--hostfile", type=str, default="/job/hostfile",
                        help="hostfile of 'hostname slots=N' lines (reference format)")
    parser.add_argument("-i", "--include", type=str, default="",
                        help='e.g. "host1,host2@0,1" — restrict hosts (and worker slots)')
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help='e.g. "host1@1" — drop hosts or specific worker slots')
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_procs", "--num_gpus", dest="num_procs", type=int,
                        default=-1, help="worker processes per node")
    parser.add_argument("--master_addr", type=str, default=None)
    parser.add_argument("--master_port", type=int, default=DEFAULT_MASTER_PORT)
    parser.add_argument("--launcher", type=str, default="auto",
                        choices=("auto", "local", "ssh", "tpu-pod"))
    parser.add_argument("--ssh_port", type=int, default=22)
    parser.add_argument("--module", action="store_true")
    parser.add_argument("--no_python", action="store_true")
    parser.add_argument("--force_multi", action="store_true",
                        help="treat as multi-node even when resources look local")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help="per-node bounded restarts after a rank failure "
                             "(see launch.py; resume from the latest committed tag)")
    parser.add_argument("--restart_backoff", type=float, default=1.0,
                        help="base seconds for the exponential restart backoff")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


# --------------------------------------------------------------------- hostfile
def parse_hostfile(path: str) -> "OrderedDict[str, int]":
    """Reference ``runner.py:parse_resource_pool`` — lines of ``hostname slots=N``."""
    resource_pool: "OrderedDict[str, int]" = OrderedDict()
    if not os.path.isfile(path):
        return resource_pool
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError(key)
                resource_pool[hostname] = int(slot_count)
            except ValueError:
                raise ValueError(f"Hostfile {path}: bad line {line!r} "
                                 "(expected 'hostname slots=N')")
    return resource_pool


def _parse_filter(spec: str) -> Dict[str, Optional[List[int]]]:
    """``host1,host2@0,1`` → {host1: None, host2: [0, 1]}."""
    out: Dict[str, Optional[List[int]]] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        # re-join slot lists split by the comma above: host@0 / 1 style handled below
        if "@" in part:
            host, slots = part.split("@", 1)
            out.setdefault(host, [])
            out[host] = sorted(set((out[host] or []) +
                                   [int(s) for s in slots.split(".") if s != ""]))
        elif part.isdigit() and out:
            last = next(reversed(out))
            if out[last] is not None:
                out[last] = sorted(set(out[last] + [int(part)]))
        else:
            out[part] = None
    return out


def filter_resources(resource_pool: "OrderedDict[str, int]",
                     include: str = "", exclude: str = "") -> "OrderedDict[str, int]":
    """Reference ``parse_inclusion_exclusion:215`` semantics, counting slots.

    Slot-level syntax uses ``@`` with dot-separated indices (``host1@0.1``); the result here
    is a per-host worker COUNT (TPU workers are symmetric — there is no per-device pinning
    like CUDA_VISIBLE_DEVICES, so selecting k slots means k workers).
    """
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")
    if include:
        inc = _parse_filter(include)
        out: "OrderedDict[str, int]" = OrderedDict()
        for host, slots in inc.items():
            if host not in resource_pool:
                raise ValueError(f"--include host {host!r} not in hostfile")
            out[host] = len(slots) if slots else resource_pool[host]
        return out
    if exclude:
        exc = _parse_filter(exclude)
        out = OrderedDict()
        for host, n in resource_pool.items():
            if host in exc:
                dropped = exc[host]
                if dropped is None:
                    continue
                remaining = n - len([s for s in dropped if s < n])
                if remaining > 0:
                    out[host] = remaining
            else:
                out[host] = n
        return out
    return OrderedDict(resource_pool)


def _is_local_host(host: str) -> bool:
    import socket
    if host in ("localhost", "127.0.0.1", "::1"):
        return True
    try:
        if host in (socket.gethostname(), socket.getfqdn()):
            return True
        # hostfiles often name this machine by IP or short alias: compare resolved
        # addresses against the addresses the local hostname resolves to
        host_addrs = {info[4][0] for info in socket.getaddrinfo(host, None)}
        local_addrs = {"127.0.0.1", "::1"}
        for local_name in (socket.gethostname(), socket.getfqdn()):
            try:
                local_addrs.update(info[4][0]
                                   for info in socket.getaddrinfo(local_name, None))
            except OSError:
                pass
        return bool(host_addrs & local_addrs)
    except OSError:
        return False


# --------------------------------------------------------------------- tpu pod env
def tpu_pod_env() -> Optional[Dict[str, str]]:
    """Identity env published by the Cloud TPU runtime on pod slices, if present."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES")
    worker_id = os.environ.get("TPU_WORKER_ID")
    if hostnames is None or worker_id is None:
        return None
    hosts = [h.strip() for h in hostnames.split(",") if h.strip()]
    return {"hosts": hosts, "worker_id": worker_id}


# --------------------------------------------------------------------- launchers
def _script_cmd(args) -> List[str]:
    if args.no_python:
        return [args.user_script] + list(args.user_args)
    base = [sys.executable, "-u"]
    if args.module:
        base.append("-m")
    return base + [args.user_script] + list(args.user_args)


def run_local(args, nproc: int) -> int:
    from . import launch
    cmd = ["--node_rank=0", "--num_nodes=1", f"--nproc_per_node={nproc}",
           f"--master_addr={args.master_addr or '127.0.0.1'}",
           f"--master_port={args.master_port}"]
    if args.module:
        cmd.append("--module")
    if args.no_python:
        cmd.append("--no_python")
    if args.max_restarts:
        cmd += [f"--max_restarts={args.max_restarts}",
                f"--restart_backoff={args.restart_backoff}"]
    cmd += [args.user_script] + list(args.user_args)
    try:
        launch.main(cmd)
    except SystemExit as e:
        return int(e.code or 0)
    return 0


def _export_env_args() -> List[str]:
    exports = []
    for key, val in os.environ.items():
        if any(key.startswith(p) for p in EXPORT_ENV_PREFIXES):
            exports.append(f"export {key}={shlex.quote(val)};")
    return exports


def run_ssh(args, resources: "OrderedDict[str, int]") -> int:
    """One ssh session per node running the per-node spawner (reference PDSHRunner)."""
    master_addr = args.master_addr or next(iter(resources))
    nproc = next(iter(resources.values()))
    if any(n != nproc for n in resources.values()):
        raise ValueError(f"heterogeneous slot counts unsupported: {dict(resources)}")
    procs = []
    for node_rank, host in enumerate(resources):
        remote = _export_env_args() + [
            f"cd {shlex.quote(os.getcwd())};",
            shlex.quote(sys.executable), "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--node_rank={node_rank}", f"--num_nodes={len(resources)}",
            f"--nproc_per_node={nproc}", f"--master_addr={shlex.quote(master_addr)}",
            f"--master_port={args.master_port}"]
        if args.module:
            remote.append("--module")
        if args.no_python:
            remote.append("--no_python")
        if args.max_restarts:
            remote += [f"--max_restarts={args.max_restarts}",
                       f"--restart_backoff={args.restart_backoff}"]
        # quote: the remote shell re-tokenizes the joined string
        remote += [shlex.quote(args.user_script)]
        remote += [shlex.quote(a) for a in args.user_args]
        ssh_cmd = ["ssh", "-p", str(args.ssh_port), "-o", "StrictHostKeyChecking=no",
                   host, " ".join(remote)]
        logger.info(f"[runner] {host}: {' '.join(remote[-6:])}")
        procs.append(subprocess.Popen(ssh_cmd))
    rc = 0
    for p in procs:
        prc = p.wait()
        rc = rc or prc
    return rc


def run_tpu_pod(args, pod: Dict) -> int:
    """Exec the user script in place with the pod coordinator env set."""
    hosts, worker_id = pod["hosts"], pod["worker_id"]
    env = os.environ
    env["COORDINATOR_ADDRESS"] = f"{args.master_addr or hosts[0]}:{args.master_port}"
    env["NPROC"] = str(len(hosts))
    env["PROCESS_ID"] = str(worker_id)
    cmd = _script_cmd(args)
    logger.info(f"[runner] tpu-pod worker {worker_id}/{len(hosts)}: exec {' '.join(cmd)}")
    os.execvpe(cmd[0], cmd, env)  # no return


def main(argv=None) -> int:
    args = parse_args(argv)
    pod = tpu_pod_env()
    launcher = args.launcher
    if launcher == "auto":
        if pod is not None:
            launcher = "tpu-pod"
        else:
            pool = filter_resources(parse_hostfile(args.hostfile),
                                    args.include, args.exclude)
            # a single host still means ssh when it isn't THIS machine
            remote_single = len(pool) == 1 and not _is_local_host(next(iter(pool)))
            launcher = "ssh" if (len(pool) > 1 or remote_single or
                                 args.force_multi) else "local"

    if launcher == "tpu-pod":
        if pod is None:
            raise RuntimeError("--launcher tpu-pod but TPU_WORKER_HOSTNAMES/"
                               "TPU_WORKER_ID are not set")
        return run_tpu_pod(args, pod)
    if launcher == "ssh":
        pool = filter_resources(parse_hostfile(args.hostfile),
                                args.include, args.exclude)
        if args.num_nodes > 0:
            pool = OrderedDict(list(pool.items())[:args.num_nodes])
        if not pool:
            raise RuntimeError(f"no hosts resolved from {args.hostfile}")
        if args.num_procs > 0:
            pool = OrderedDict((h, args.num_procs) for h in pool)
        return run_ssh(args, pool)
    # local: --num_procs wins; else a single-host hostfile's slot count; else 1
    nproc = args.num_procs
    if nproc <= 0:
        pool = filter_resources(parse_hostfile(args.hostfile),
                                args.include, args.exclude)
        nproc = next(iter(pool.values())) if len(pool) == 1 else 1
    return run_local(args, nproc)


if __name__ == "__main__":
    sys.exit(main())
