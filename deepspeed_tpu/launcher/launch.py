"""Per-node process spawner.

TPU-native analogue of reference ``deepspeed/launcher/launch.py`` (``main:129``): given this
node's rank and the world layout, spawn one Python process per local worker with the
coordinator env contract that ``comm.init_distributed`` consumes
(``COORDINATOR_ADDRESS``/``NPROC``/``PROCESS_ID``/``LOCAL_RANK``), forward SIGINT/SIGTERM to
the children, and propagate the first failure (killing the stragglers) — the reference's
sig_names/поll loop, minus CUDA_VISIBLE_DEVICES bookkeeping which has no TPU analogue (chips
are assigned by the TPU runtime per process via ``TPU_PROCESS_BOUNDS``-style env, or shared
under a single process).
"""

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List

from ..utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="deepspeed_tpu per-node launcher")
    parser.add_argument("--node_rank", type=int, default=0,
                        help="rank of this node in the job")
    parser.add_argument("--num_nodes", type=int, default=1)
    parser.add_argument("--nproc_per_node", type=int, default=1,
                        help="worker processes to spawn on this node")
    parser.add_argument("--master_addr", type=str, default="127.0.0.1",
                        help="coordinator host (jax.distributed rendezvous)")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--module", action="store_true",
                        help="interpret the script as a python module (python -m)")
    parser.add_argument("--no_python", action="store_true",
                        help="exec the script directly, not via the python interpreter")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help="restart the whole worker group up to N times after a "
                             "rank failure (training scripts resume from the latest "
                             "committed checkpoint tag)")
    parser.add_argument("--restart_backoff", type=float, default=1.0,
                        help="base seconds between restarts (exponential: "
                             "base * 2**attempt)")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args)


def build_cmd(args) -> List[str]:
    if args.no_python:
        cmd = [args.training_script]
    elif args.module:
        cmd = [sys.executable, "-u", "-m", args.training_script]
    else:
        cmd = [sys.executable, "-u", args.training_script]
    return cmd + list(args.training_script_args)


def _spawn_group(args, world_size: int, cmd: List[str],
                 attempt: int) -> List[subprocess.Popen]:
    processes: List[subprocess.Popen] = []
    for local_rank in range(args.nproc_per_node):
        env = os.environ.copy()
        env["COORDINATOR_ADDRESS"] = f"{args.master_addr}:{args.master_port}"
        env["MASTER_ADDR"] = args.master_addr
        env["MASTER_PORT"] = str(args.master_port)
        env["NPROC"] = env["WORLD_SIZE"] = str(world_size)
        env["PROCESS_ID"] = env["RANK"] = str(
            args.node_rank * args.nproc_per_node + local_rank)
        env["LOCAL_RANK"] = str(local_rank)
        env["NODE_RANK"] = str(args.node_rank)
        env["DS_TPU_RESTART_ATTEMPT"] = str(attempt)
        logger.info(f"[launch] node {args.node_rank} local {local_rank} -> "
                    f"rank {env['RANK']}/{world_size}"
                    f"{f' (restart {attempt})' if attempt else ''}: "
                    f"{' '.join(cmd)}")
        processes.append(subprocess.Popen(cmd, env=env))
    return processes


def _wait_group(processes: List[subprocess.Popen]) -> int:
    """Reference launch.py poll loop: first non-zero exit kills the rest,
    escalating terminate -> kill so a worker stuck in a collective (SIGTERM
    pending) can't hang us. Returns the first failing exit code (0 = clean)."""
    exit_code = 0
    kill_deadline = None
    alive = list(processes)
    while alive:
        time.sleep(0.1)
        if kill_deadline is not None and time.monotonic() > kill_deadline:
            for q in alive:
                try:
                    q.kill()
                except OSError:
                    pass
            kill_deadline = None
        for p in list(alive):
            rc = p.poll()
            if rc is None:
                continue
            alive.remove(p)
            if rc != 0 and exit_code == 0:
                exit_code = rc
                logger.error(f"[launch] rank process {p.args!r} failed with {rc}; "
                             "terminating remaining workers")
                kill_deadline = time.monotonic() + 15.0
                for q in alive:
                    try:
                        q.terminate()
                    except OSError:
                        pass
    return exit_code


def main(args=None):
    args = parse_args(args)
    world_size = args.num_nodes * args.nproc_per_node
    cmd = build_cmd(args)

    processes: List[subprocess.Popen] = []
    signaled = {"got": None}

    def forward_signal(signum, frame):
        signaled["got"] = signum      # operator/scheduler stop: no restart
        for p in processes:
            if p.poll() is None:
                try:
                    p.send_signal(signum)
                except OSError:
                    pass

    signal.signal(signal.SIGINT, forward_signal)
    signal.signal(signal.SIGTERM, forward_signal)

    # bounded rank-failure restarts (reference torchelastic max_restarts): a
    # crash/wedge respawns the WHOLE group after exponential backoff; training
    # scripts resume from the latest committed checkpoint tag. Single-node
    # scope: multi-node jobs restart through the scheduler (the whole-slice
    # replacement discipline, see elastic_agent.py docstring).
    max_restarts = max(0, args.max_restarts)
    if max_restarts and args.num_nodes > 1:
        logger.warning("[launch] --max_restarts on a multi-node job restarts "
                       "only this node's workers; the coordinator contract "
                       "requires ALL nodes to restart — prefer scheduler-level "
                       "restarts for multi-node")
    exit_code = 0
    for attempt in range(max_restarts + 1):
        processes[:] = _spawn_group(args, world_size, cmd, attempt)
        exit_code = _wait_group(processes)
        if exit_code == 0:
            break
        if signaled["got"] is not None:
            logger.info(f"[launch] stopped by signal {signaled['got']}; "
                        "not restarting")
            break
        if attempt < max_restarts:
            delay = args.restart_backoff * (2 ** attempt)
            logger.error(f"[launch] worker group failed (exit {exit_code}); "
                         f"restart {attempt + 1}/{max_restarts} in {delay:.1f}s")
            time.sleep(delay)
            # a stop signal delivered DURING the backoff sleep must also
            # suppress the respawn (PEP 475 resumes the sleep after the
            # handler runs, so the loop-top check alone would miss it)
            if signaled["got"] is not None:
                logger.info(f"[launch] stopped by signal {signaled['got']} "
                            "during backoff; not restarting")
                break
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
