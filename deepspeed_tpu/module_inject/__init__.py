from .replace_module import (HF_POLICIES, convert_hf_model, replace_transformer_layer)
