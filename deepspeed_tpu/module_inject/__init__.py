from .replace_module import (HF_POLICIES, convert_hf_model, convert_training_model,
                             replace_transformer_layer)
from .diffusers_policies import (convert_clip_text, convert_unet_state_dict,
                                 convert_vae_decoder_state_dict)
