from .replace_module import (HF_POLICIES, convert_hf_model, convert_training_model,
                             replace_transformer_layer)
