"""Encoder injection policies: HF BERT / DistilBERT → :class:`EncoderLM` params.

Reference ``module_inject/containers/bert.py:1`` + ``distil_bert.py:1``
(``replace_policy.py`` registry): the weight-layout converters for the
bidirectional half of the injection surface. Outputs are parity-checked against
the HF modules (``tests/unit/inference/test_encoder_inference.py``).
"""

from typing import Any, Dict, Tuple

import numpy as np

from ..models.encoder import EncoderConfig, bert_cfg, distilbert_cfg
from ..utils.logging import logger


def _t(w) -> np.ndarray:
    """torch Linear weight (out, in) → flax Dense kernel (in, out)."""
    return np.ascontiguousarray(w.detach().cpu().numpy().T.astype(np.float32))


def _v(w) -> np.ndarray:
    return np.ascontiguousarray(w.detach().cpu().numpy().astype(np.float32))


def _dense(lin) -> Dict[str, np.ndarray]:
    return {"kernel": _t(lin.weight), "bias": _v(lin.bias)}


def _ln(ln) -> Dict[str, np.ndarray]:
    return {"scale": _v(ln.weight), "bias": _v(ln.bias)}


def convert_bert(model) -> Tuple[EncoderConfig, Any]:
    """HF ``BertModel`` (or the encoder inside ``BertFor*``) → EncoderLM."""
    if hasattr(model, "bert"):
        model = model.bert
    hf = model.config
    cfg = bert_cfg(vocab_size=hf.vocab_size,
                   max_seq_len=hf.max_position_embeddings,
                   type_vocab_size=hf.type_vocab_size,
                   n_embd=hf.hidden_size, n_layer=hf.num_hidden_layers,
                   n_head=hf.num_attention_heads,
                   d_ff=hf.intermediate_size, ln_eps=hf.layer_norm_eps,
                   pooler=model.pooler is not None)
    emb = model.embeddings
    params: Dict[str, Any] = {
        "wte": _v(emb.word_embeddings.weight),
        "wpe": _v(emb.position_embeddings.weight),
        "tte": _v(emb.token_type_embeddings.weight),
        "ln_embed": _ln(emb.LayerNorm),
    }
    for i, layer in enumerate(model.encoder.layer):
        params[f"layers_{i}"] = {
            "q_proj": _dense(layer.attention.self.query),
            "k_proj": _dense(layer.attention.self.key),
            "v_proj": _dense(layer.attention.self.value),
            "o_proj": _dense(layer.attention.output.dense),
            "ln_attn": _ln(layer.attention.output.LayerNorm),
            "fc_in": _dense(layer.intermediate.dense),
            "fc_out": _dense(layer.output.dense),
            "ln_mlp": _ln(layer.output.LayerNorm),
        }
    if cfg.pooler:
        params["pooler"] = _dense(model.pooler.dense)
    logger.info(f"converted HF bert: L{cfg.n_layer} d{cfg.n_embd}")
    return cfg, params


def convert_distilbert(model) -> Tuple[EncoderConfig, Any]:
    """HF ``DistilBertModel`` → EncoderLM (no token types, no pooler)."""
    if hasattr(model, "distilbert"):
        model = model.distilbert
    hf = model.config
    cfg = distilbert_cfg(vocab_size=hf.vocab_size,
                         max_seq_len=hf.max_position_embeddings,
                         n_embd=hf.dim, n_layer=hf.n_layers, n_head=hf.n_heads,
                         d_ff=hf.hidden_dim, ln_eps=1e-12)
    emb = model.embeddings
    params: Dict[str, Any] = {
        "wte": _v(emb.word_embeddings.weight),
        "wpe": _v(emb.position_embeddings.weight),
        "ln_embed": _ln(emb.LayerNorm),
    }
    for i, layer in enumerate(model.transformer.layer):
        params[f"layers_{i}"] = {
            "q_proj": _dense(layer.attention.q_lin),
            "k_proj": _dense(layer.attention.k_lin),
            "v_proj": _dense(layer.attention.v_lin),
            "o_proj": _dense(layer.attention.out_lin),
            "ln_attn": _ln(layer.sa_layer_norm),
            "fc_in": _dense(layer.ffn.lin1),
            "fc_out": _dense(layer.ffn.lin2),
            "ln_mlp": _ln(layer.output_layer_norm),
        }
    logger.info(f"converted HF distilbert: L{cfg.n_layer} d{cfg.n_embd}")
    return cfg, params


ENCODER_POLICIES = {"bert": convert_bert, "distilbert": convert_distilbert}


def is_hf_encoder(model) -> bool:
    return getattr(getattr(model, "config", None), "model_type", None) \
        in ENCODER_POLICIES


def convert_hf_encoder(model) -> Tuple[EncoderConfig, Any]:
    model_type = model.config.model_type
    return ENCODER_POLICIES[model_type](model)
