"""Automatic policy for unknown HF decoder architectures.

Reference ``deepspeed/module_inject/auto_tp.py`` (``AutoTP.tp_parser``): when no named
injection policy exists, the reference walks the module tree, classifies Linears into
all-reduce (row-parallel) vs sliced (column-parallel) by name, and shards generically.
The TPU analogue classifies by the same name conventions but emits a
:class:`~..models.causal_lm.CausalLMConfig` + converted parameter tree — after which
tensor parallelism falls out of ``causal_lm_param_specs`` exactly as for named policies
(column/row classification happens once, in the spec rules, not per-model).

Scope (documented, fail-loud): decoder-only causal LMs whose blocks are expressible in
the :class:`CausalLM` knob space — separate or fused qkv (MHA fused layouts are
per-head interleaved per the HF convention; GQA/MQA fused layouts are contiguous
``[Q|K|V]`` blocks), learned/rotary/alibi positions, gated or plain MLP, pre-LN.
Unrecognised per-layer parameters raise rather than being silently dropped.
"""

import re
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..models.causal_lm import CausalLMConfig
from ..utils.logging import logger
from .replace_module import _np, _split_fused_qkv

# within-layer parameter-name alternatives, in precedence order (reference auto_tp's
# name census, plus the fused-qkv spellings its named containers handle)
_LAYER_RE = re.compile(r"(?:^|\.)(?:h|layers|blocks|decoder\.layers)\.(\d+)\.")
_NAMES = {
    "ln_attn": ("ln_1", "input_layernorm", "self_attn_layer_norm", "attention_norm",
                "ln_attn"),
    "ln_mlp": ("ln_2", "post_attention_layernorm", "final_layer_norm", "ffn_norm",
               "ln_mlp"),
    "q": ("attn.q_proj", "self_attn.q_proj", "attention.q_proj", "q_proj"),
    "k": ("attn.k_proj", "self_attn.k_proj", "attention.k_proj", "k_proj"),
    "v": ("attn.v_proj", "self_attn.v_proj", "attention.v_proj", "v_proj"),
    # fused-qkv spellings are an ALLOWLIST of layouts this module provably splits
    # correctly (gpt_bigcode family: MHA per-head interleaved / MQA-GQA contiguous,
    # verified against HF logits). 'query_key_value' (falcon: per-kv-group interleave)
    # and 'qkv_proj' (codegen: mp_num-blocked) are deliberately ABSENT — those
    # layouts differ and must fail loud ("needs a named policy"), not mis-split.
    "qkv": ("attn.c_attn",),
    "o": ("attn.c_proj", "self_attn.o_proj", "attention.o_proj", "o_proj",
          "self_attention.dense", "attn.out_proj", "self_attn.out_proj",
          "attention.dense"),
    "gate": ("mlp.gate_proj",),
    "up": ("mlp.up_proj",),
    "fc_in": ("mlp.c_fc", "mlp.fc_in", "mlp.dense_h_to_4h", "fc1", "mlp.fc1",
              "mlp.w_in"),
    "fc_out": ("mlp.c_proj", "mlp.fc_out", "mlp.down_proj", "mlp.dense_4h_to_h",
               "fc2", "mlp.fc2", "mlp.w_out"),
}
_EMBED = ("wte.weight", "embed_tokens.weight", "word_embeddings.weight",
          "embed_in.weight")
_POS = ("wpe.weight", "embed_positions.weight", "position_embeddings.weight")
_FINAL_LN = ("ln_f", "final_layernorm", "norm", "final_layer_norm")


def _cfg_get(cfg, *names, default=None):
    for n in names:
        if getattr(cfg, n, None) is not None:
            return getattr(cfg, n)
    return default


def _find(layer_sd: Dict[str, Any], role: str, suffix: str,
          consumed: Optional[set] = None, raw: bool = False):
    """First matching parameter for ``role``; records the matched key in
    ``consumed`` so leftover (unrecognised) parameters can fail loud."""
    for cand in _NAMES[role]:
        key = f"{cand}.{suffix}"
        if key in layer_sd:
            if consumed is not None:
                consumed.add(key)
            return layer_sd[key] if raw else _np(layer_sd[key])
    return None


def infer_config(model) -> CausalLMConfig:
    """Map an HF config onto the CausalLM knob space (reference: what each named
    container hard-codes, read generically)."""
    c = model.config
    sd_keys = list(model.state_dict().keys())
    d = _cfg_get(c, "n_embd", "hidden_size")
    n_layer = _cfg_get(c, "n_layer", "num_hidden_layers")
    n_head = _cfg_get(c, "n_head", "num_attention_heads")
    if not (d and n_layer and n_head):
        raise AssertionError(f"auto-TP cannot infer dims from {type(c).__name__}")
    n_kv = _cfg_get(c, "num_key_value_heads", "num_kv_heads")
    if getattr(c, "multi_query", False):
        n_kv = 1
    pos = "learned" if any(k.endswith(p) for p in _POS for k in sd_keys) else None
    if pos is None:
        if getattr(c, "alibi", False) or getattr(c, "use_alibi", False):
            pos = "alibi"
        elif _cfg_get(c, "rope_theta", "rotary_emb_base") is not None or \
                any("rotary" in k for k in sd_keys):
            pos = "rotary"
        else:
            pos = "none"
    act = str(_cfg_get(c, "activation_function", "hidden_act",
                       default="gelu")).lower()
    act = ("gelu" if "gelu" in act else
           "silu" if act in ("silu", "swish") else
           "relu" if "relu" in act else "gelu")
    gated = any(".mlp.gate_proj." in k for k in sd_keys)
    # norm flavor: trust the config (rms_norm_eps is the HF convention); a bias-free
    # attention norm WITHOUT that attribute is ambiguous (could be LayerNorm(bias=False))
    # and must fail loud rather than silently drop the mean subtraction
    rms = getattr(c, "rms_norm_eps", None) is not None
    ln_has_bias = any(any(f"{n}.bias" in k for n in _NAMES["ln_attn"])
                      for k in sd_keys)
    if not rms and not ln_has_bias:
        raise ValueError(
            "auto-TP: attention norm has no bias and the config has no rms_norm_eps "
            "— cannot distinguish RMSNorm from bias-free LayerNorm; provide a named "
            "policy for this architecture")
    rotary_pct = float(_cfg_get(c, "partial_rotary_factor", "rotary_pct",
                                default=1.0))
    qkv_bias = any(any(f"{n}.bias" in k for n in (_NAMES["q"] + _NAMES["qkv"]))
                   for k in sd_keys)
    mlp_bias = any(any(f"{n}.bias" in k for n in _NAMES["fc_out"]) for k in sd_keys)
    tied = bool(getattr(c, "tie_word_embeddings", True))
    return CausalLMConfig(
        vocab_size=c.vocab_size,
        max_seq_len=_cfg_get(c, "n_positions", "max_position_embeddings",
                             default=2048),
        n_embd=d, n_layer=n_layer, n_head=n_head, n_kv_head=n_kv,
        d_ff=_cfg_get(c, "n_inner", "intermediate_size", "ffn_dim"),
        pos_emb=pos, rotary_pct=rotary_pct,
        rotary_base=float(_cfg_get(c, "rope_theta", "rotary_emb_base",
                                   default=10000.0)),
        parallel_residual=bool(_cfg_get(c, "use_parallel_residual",
                                        "parallel_attn", default=False)),
        gated_mlp=gated, activation=act,
        layernorm="rmsnorm" if rms else "layernorm",
        ln_eps=float(_cfg_get(c, "layer_norm_epsilon", "layer_norm_eps",
                              "rms_norm_eps", default=1e-5)),
        tie_word_embeddings=tied, qkv_bias=qkv_bias, mlp_bias=mlp_bias,
        name=f"auto:{getattr(c, 'model_type', type(c).__name__)}")


def _split_contiguous_qkv(w: np.ndarray, b: Optional[np.ndarray], d: int,
                          kv_dim: int):
    """Fused (d + 2·kv_dim, in) torch weight → q/k/v (GQA/MQA contiguous blocks)."""
    if w.shape[0] != d + 2 * kv_dim and w.shape[1] == d + 2 * kv_dim:
        w = w.T    # Conv1D layout (in, out)
    if not (w.shape[0] == d + 2 * kv_dim):
        raise AssertionError((w.shape, d, kv_dim))
    qw, kw, vw = np.split(w, [d, d + kv_dim], axis=0)
    qb = kb = vb = None
    if b is not None:
        qb, kb, vb = np.split(b, [d, d + kv_dim])
    return (qw, qb), (kw, kb), (vw, vb)


def _proj(w: np.ndarray, b: Optional[np.ndarray], in_dim: int) -> Dict[str, Any]:
    """torch weight → flax {kernel (in, out), bias}. Disambiguates torch Linear
    (out, in) from GPT-2-style Conv1D (in, out) by the known input dim; square
    matrices assume torch Linear (Conv1D architectures all have named policies)."""
    if w.shape[1] == in_dim:          # torch Linear (out, in) — also the square case
        kernel = jnp.asarray(w.T)
    else:
        if not (w.shape[0] == in_dim):
            raise AssertionError((w.shape, in_dim))
        kernel = jnp.asarray(w)       # Conv1D already (in, out)
    out = {"kernel": kernel}
    if b is not None:
        out["bias"] = jnp.asarray(b)
    return out


def auto_convert_hf_model(model) -> Tuple[CausalLMConfig, Any]:
    """Generic HF → CausalLM conversion for architectures without a named policy.

    Raises with the missing-name census when the architecture's parameters don't
    match the recognised conventions (fail-loud, like the reference's
    'Please provide policy' assert)."""
    cfg = infer_config(model)
    sd = model.state_dict()
    d, kv_dim = cfg.n_embd, cfg.kv_heads * cfg.head_dim

    # strip the common trunk prefix ("transformer."/"model."/"gpt_neox.")
    layers: Dict[int, Dict[str, Any]] = {}
    trunk: Dict[str, Any] = {}
    for k, v in sd.items():
        m = _LAYER_RE.search(k)
        if m:
            li = int(m.group(1))
            layers.setdefault(li, {})[k[m.end():]] = v
        else:
            trunk[k] = v
    if not (len(layers) == cfg.n_layer):
        raise AssertionError(f"auto-TP found {len(layers)} transformer layers, config says "
         f"{cfg.n_layer}; keys sample: {list(sd)[:5]}")

    params: Dict[str, Any] = {}
    trunk_left = set()
    for name, v in trunk.items():
        if any(name.endswith(e) for e in _EMBED):
            params["wte"] = jnp.asarray(_np(v))
        elif any(name.endswith(p) for p in _POS):
            params["wpe"] = jnp.asarray(_np(v))
        elif any(f"{ln}.weight" in name for ln in _FINAL_LN) and v.ndim == 1:
            params.setdefault("ln_f", {})["scale"] = jnp.asarray(_np(v))
        elif any(f"{ln}.bias" in name for ln in _FINAL_LN) and v.ndim == 1:
            params.setdefault("ln_f", {})["bias"] = jnp.asarray(_np(v))
        elif name.endswith("lm_head.weight"):
            if not cfg.tie_word_embeddings:
                params["lm_head"] = {"kernel": jnp.asarray(_np(v).T)}
            # tied: the key is a duplicate view of wte — consumed either way
        elif "inv_freq" in name or name.endswith("position_ids"):
            pass   # rotary/positions buffers, not parameters
        else:
            trunk_left.add(name)
    # same fail-loud census as the layer loop: silently dropping trunk params
    # (embedding layernorms, differently-spelled heads) would serve wrong logits
    if trunk_left:
        raise ValueError(
            f"auto-TP: unrecognised non-layer parameters {sorted(trunk_left)} — "
            "this architecture needs a named policy")
    if not ("wte" in params):
        raise AssertionError(f"auto-TP: no token embedding among {list(trunk)[:8]}")
    if not ("ln_f" in params):
        raise AssertionError(f"auto-TP: no final norm among {list(trunk)[:8]}")

    # buffers that are legitimately not parameters of the CausalLM tree
    _IGNORABLE = ("inv_freq", "attn.bias", "attn.masked_bias",
                  "attention.bias", "attention.masked_bias")
    for li in range(cfg.n_layer):
        lsd = layers[li]
        used: set = set()
        out: Dict[str, Any] = {}
        for role, ours in [("ln_attn", "ln_attn"), ("ln_mlp", "ln_mlp")]:
            w = _find(lsd, role, "weight", used)
            if not (w is not None):
                raise AssertionError(f"auto-TP: layer {li} missing {role} (keys: {sorted(lsd)[:10]})")
            out[ours] = {"scale": jnp.asarray(w)}
            b = _find(lsd, role, "bias", used)
            if b is not None:
                out[ours]["bias"] = jnp.asarray(b)

        if _find(lsd, "q", "weight") is not None:
            for role, ours in [("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj")]:
                w = _find(lsd, role, "weight", used)
                out[ours] = _proj(w, _find(lsd, role, "bias", used), d)
        else:
            w = _find(lsd, "qkv", "weight", used, raw=True)
            if not (w is not None):
                raise AssertionError(f"auto-TP: layer {li} has neither split nor fused qkv")
            b = _find(lsd, "qkv", "bias", used, raw=True)
            if cfg.kv_heads == cfg.n_head:
                # HF convention for MHA fused qkv is PER-HEAD interleaved
                # [q_h|k_h|v_h] (gpt_bigcode MHA views (B,T,heads,3·dh); neox/bloom
                # likewise) — the shared splitter undoes it
                q_p, k_p, v_p = _split_fused_qkv(w, b, cfg.n_head, cfg.head_dim,
                                                 interleaved=True)
                out["q_proj"], out["k_proj"], out["v_proj"] = q_p, k_p, v_p
            else:
                # GQA/MQA fused layouts are contiguous [Q | K | V] blocks
                for ours, (pw, pb) in zip(
                        ("q_proj", "k_proj", "v_proj"),
                        _split_contiguous_qkv(_np(w),
                                              None if b is None else _np(b),
                                              d, kv_dim)):
                    out[ours] = {"kernel": jnp.asarray(pw.T)}
                    if pb is not None:
                        out[ours]["bias"] = jnp.asarray(pb)

        ow = _find(lsd, "o", "weight", used)
        if not (ow is not None):
            raise AssertionError(f"auto-TP: layer {li} missing attention out proj")
        out["o_proj"] = _proj(ow, _find(lsd, "o", "bias", used), d)

        if cfg.gated_mlp:
            out["gate_proj"] = _proj(_find(lsd, "gate", "weight", used),
                                     _find(lsd, "gate", "bias", used), d)
            out["up_proj"] = _proj(_find(lsd, "up", "weight", used),
                                   _find(lsd, "up", "bias", used), d)
        else:
            fw = _find(lsd, "fc_in", "weight", used)
            if not (fw is not None):
                raise AssertionError(f"auto-TP: layer {li} missing mlp in-proj")
            out["fc_in"] = _proj(fw, _find(lsd, "fc_in", "bias", used), d)
        dw = _find(lsd, "fc_out", "weight", used)
        if not (dw is not None):
            raise AssertionError(f"auto-TP: layer {li} missing mlp out-proj")
        out["fc_out"] = _proj(dw, _find(lsd, "fc_out", "bias", used), cfg.ffn_dim)

        # fail-loud: any unconsumed layer parameter means the architecture has
        # structure the CausalLM knob space does not express (q/k norms, relative
        # position biases, ...) — silent dropping would serve wrong logits
        leftovers = {k for k in lsd if k not in used
                     and not any(k.endswith(ig) for ig in _IGNORABLE)}
        if leftovers:
            raise ValueError(
                f"auto-TP: layer {li} has unrecognised parameters {sorted(leftovers)} "
                "— this architecture needs a named policy")
        params[f"layers_{li}"] = out

    logger.info(f"auto-TP policy: converted {cfg.name} "
                f"(L{cfg.n_layer}, d{cfg.n_embd}, h{cfg.n_head}/kv{cfg.kv_heads}, "
                f"{cfg.pos_emb}, {'gated ' if cfg.gated_mlp else ''}{cfg.activation})")
    return cfg, params
