"""Diffusers/CLIP weight injection — the ``generic_injection`` equivalent.

The reference patches optimized containers into HF diffusers pipelines
(``module_inject/replace_module.py:213`` ``generic_injection`` routing UNet/VAE/
CLIP through ``containers/unet.py:1`` / ``vae.py:1`` / ``clip.py:1``). Here the
flax modules in ``models/diffusion.py`` name every submodule after its diffusers
state-dict path, so conversion is a NORMALIZED-NAME JOIN: both sides flatten to
the same underscore string (torch ``down_blocks.0.attentions.0.transformer_blocks
.0.attn1.to_q.weight`` ≡ flax path ``down_blocks_0_attentions_0 / transformer_
blocks_0 / attn1 / to_q / kernel``), and each tensor converts by the abstract
flax leaf: conv OIHW → HWIO, linear (O,I) → (I,O), norm weight → scale. Every
unmatched or shape-mismatched tensor is reported — the conversion validates the
format contract instead of trusting it.

No dependency on the diffusers package: conversion consumes plain torch state
dicts (synthesized in diffusers naming in tests; CLIP pinned against the real
``transformers`` torch module).
"""

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.diffusion import (CLIPTextConfig, UNet2DCondition, UNetConfig,
                                VAEConfig, VAEDecoder)

_LEAF_TO_TORCH = {"kernel": "weight", "scale": "weight"}


def _np(t) -> np.ndarray:
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t)


def _index_abstract(abstract_params) -> Dict[str, Tuple[tuple, Any]]:
    """{normalized torch-style name: (flax key path, abstract leaf)}."""
    flat = jax.tree_util.tree_flatten_with_path(abstract_params)[0]
    index = {}
    for path, leaf in flat:
        parts = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
        torch_leaf = _LEAF_TO_TORCH.get(parts[-1], parts[-1])
        index["_".join(parts[:-1] + [torch_leaf])] = (path, leaf)
    return index


def _convert_leaf(flax_name: str, abstract, arr: np.ndarray) -> np.ndarray:
    if flax_name == "kernel":
        if arr.ndim == 4:                      # conv OIHW → HWIO
            arr = arr.transpose(2, 3, 1, 0)
        elif arr.ndim == 2:                    # linear (O, I) → (I, O)
            arr = arr.T
    return arr


def convert_to_flax(sd: Dict[str, Any], module, *sample_args,
                    skip_prefixes: Tuple[str, ...] = ()) -> Any:
    """Torch state dict → flax params for ``module`` (shape-validated)."""
    abstract = jax.eval_shape(
        lambda: module.init(jax.random.PRNGKey(0), *sample_args))["params"]
    index = _index_abstract(abstract)
    filled: Dict[str, Any] = {}
    unmatched, mismatched = [], []
    for key, t in sd.items():
        if any(key.startswith(p) for p in skip_prefixes):
            continue
        norm = key.replace(".", "_")
        if norm not in index:
            unmatched.append(key)
            continue
        path, leaf = index[norm]
        flax_name = str(getattr(path[-1], "key", path[-1]))
        arr = _convert_leaf(flax_name, leaf, _np(t))
        if tuple(arr.shape) != tuple(leaf.shape):
            mismatched.append((key, arr.shape, tuple(leaf.shape)))
            continue
        node = filled
        for p in path[:-1]:
            node = node.setdefault(str(getattr(p, "key", p)), {})
        node[flax_name] = jnp.asarray(arr)
    seen = {k.replace(".", "_") for k in sd
            if not any(k.startswith(p) for p in skip_prefixes)}
    missing = [n for n in index if n not in seen]
    if unmatched or mismatched or missing:
        raise ValueError(
            "diffusers conversion failed the format contract:\n"
            f"  unmatched torch keys: {sorted(unmatched)[:6]}\n"
            f"  shape mismatches (key, got, want): {mismatched[:6]}\n"
            f"  missing flax params: {sorted(missing)[:6]}")
    return filled


def convert_unet_state_dict(sd: Dict[str, Any], config: UNetConfig) -> Any:
    """Diffusers ``UNet2DConditionModel`` state dict → flax params for
    :class:`~.models.diffusion.UNet2DCondition` (reference
    ``containers/unet.py:1``)."""
    s = config.sample_size
    sample = jnp.zeros((1, s, s, config.in_channels), jnp.float32)
    t = jnp.zeros((1,), jnp.int32)
    ctx = jnp.zeros((1, 8, config.cross_attention_dim), jnp.float32)
    return convert_to_flax(sd, UNet2DCondition(config), sample, t, ctx)


def convert_vae_decoder_state_dict(sd: Dict[str, Any],
                                   config: VAEConfig) -> Any:
    """Diffusers ``AutoencoderKL`` state dict (decoder half + post_quant_conv) →
    flax params for :class:`~.models.diffusion.VAEDecoder`; encoder tensors are
    skipped (reference ``containers/vae.py:1`` serves the same decode path)."""
    z = jnp.zeros((1, 8, 8, config.latent_channels), jnp.float32)
    return convert_to_flax(sd, VAEDecoder(config), z,
                           skip_prefixes=("encoder.", "quant_conv"))


def convert_clip_text(model) -> Tuple[CLIPTextConfig, Any]:
    """HF torch ``CLIPTextModel`` → (config, flax params) for
    :class:`~.models.diffusion.CLIPTextEncoder` (reference
    ``containers/clip.py:1``). Output parity is pinned in
    ``tests/unit/inference/test_diffusion.py``."""
    hf = model.config
    cfg = CLIPTextConfig(
        vocab_size=hf.vocab_size,
        max_position_embeddings=hf.max_position_embeddings,
        hidden_size=hf.hidden_size, num_hidden_layers=hf.num_hidden_layers,
        num_attention_heads=hf.num_attention_heads,
        intermediate_size=hf.intermediate_size,
        ln_eps=getattr(hf, "layer_norm_eps", 1e-5))
    act = getattr(hf, "hidden_act", "quick_gelu")
    if act not in ("quick_gelu", "gelu"):
        raise ValueError(f"CLIP hidden_act={act!r} unsupported "
                         "(quick_gelu and gelu are wired)")
    cfg.act = act
    sd = model.state_dict()
    pfx = "text_model." if any(k.startswith("text_model.") for k in sd) else ""

    def g(key):
        return jnp.asarray(_np(sd[pfx + key]))

    params: Dict[str, Any] = {
        "token_embedding": g("embeddings.token_embedding.weight"),
        "position_embedding": g("embeddings.position_embedding.weight"),
        "final_layer_norm": {"scale": g("final_layer_norm.weight"),
                             "bias": g("final_layer_norm.bias")},
    }
    for i in range(cfg.num_hidden_layers):
        lp = f"encoder.layers.{i}"
        for ours, theirs in (
                (f"layers_{i}_layer_norm1", f"{lp}.layer_norm1"),
                (f"layers_{i}_layer_norm2", f"{lp}.layer_norm2")):
            params[ours] = {"scale": g(f"{theirs}.weight"),
                            "bias": g(f"{theirs}.bias")}
        for ours, theirs in (
                (f"layers_{i}_q_proj", f"{lp}.self_attn.q_proj"),
                (f"layers_{i}_k_proj", f"{lp}.self_attn.k_proj"),
                (f"layers_{i}_v_proj", f"{lp}.self_attn.v_proj"),
                (f"layers_{i}_out_proj", f"{lp}.self_attn.out_proj"),
                (f"layers_{i}_fc1", f"{lp}.mlp.fc1"),
                (f"layers_{i}_fc2", f"{lp}.mlp.fc2")):
            params[ours] = {"kernel": g(f"{theirs}.weight").T,
                            "bias": g(f"{theirs}.bias")}
    return cfg, params
