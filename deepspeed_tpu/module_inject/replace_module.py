"""HF model conversion policies — the module-injection analogue.

Reference: ``deepspeed/module_inject/replace_module.py`` (``replace_transformer_layer:308``,
``ReplaceWithTensorSlicing:25``) + per-architecture containers
(``module_inject/containers/{gpt2,bloom,opt,gptneox,gptj,llama...}.py``).

On TPU there is no module surgery: a policy maps an HF architecture to (a) a
:class:`CausalLMConfig` instance and (b) a weight-layout conversion from the torch
state_dict into the :class:`CausalLM` param tree. Tensor slicing happens afterwards at
placement time via PartitionSpecs (``models/causal_lm.py:causal_lm_param_specs``) — the
compile-time equivalent of ``ReplaceWithTensorSlicing``.
"""

from typing import Any, Callable, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from ..models.causal_lm import (CausalLMConfig, bloom_cfg, gpt2_cfg, gptj_cfg,
                                gptneox_cfg, llama_cfg, opt_cfg)
from ..utils.logging import logger


def _np(tensor) -> np.ndarray:
    return np.asarray(tensor.detach().cpu().float().numpy())


def _kernel(w) -> jnp.ndarray:
    """torch Linear weight (out, in) → flax kernel (in, out)."""
    return jnp.asarray(_np(w).T)


def _vec(b) -> jnp.ndarray:
    return jnp.asarray(_np(b))


def _ln(sd, prefix) -> Dict:
    return {"scale": _vec(sd[f"{prefix}.weight"]), "bias": _vec(sd[f"{prefix}.bias"])}


def _split_fused_qkv(w, b, n_head, head_dim, interleaved: bool):
    """Fused qkv → separate q/k/v flax kernels.

    ``interleaved``: BLOOM/NeoX store (h, 3, dh) per-head interleaved; GPT-2 stores
    concatenated [q|k|v] blocks.
    """
    d = n_head * head_dim
    wk = _np(w)                              # torch (3d, in) or Conv1D (in, 3d)
    if wk.shape[0] == 3 * d:                 # torch Linear layout
        wk = wk.T                            # (in, 3d)
    if interleaved:
        wk = wk.reshape(wk.shape[0], n_head, 3, head_dim)
        q = wk[:, :, 0].reshape(wk.shape[0], d)
        k = wk[:, :, 1].reshape(wk.shape[0], d)
        v = wk[:, :, 2].reshape(wk.shape[0], d)
    else:
        q, k, v = np.split(wk, 3, axis=1)
    out = [{"kernel": jnp.asarray(x)} for x in (q, k, v)]
    if b is not None:
        bk = _np(b)
        if interleaved:
            bk = bk.reshape(n_head, 3, head_dim)
            bs = [bk[:, i].reshape(d) for i in range(3)]
        else:
            bs = np.split(bk, 3)
        for o, bb in zip(out, bs):
            o["bias"] = jnp.asarray(bb)
    return out


# --------------------------------------------------------------------------- policies
def _convert_gpt2(model) -> Tuple[CausalLMConfig, Any]:
    hf = model.config
    cfg = gpt2_cfg(vocab_size=hf.vocab_size, max_seq_len=hf.n_positions,
                   n_embd=hf.n_embd, n_layer=hf.n_layer, n_head=hf.n_head)
    sd = model.state_dict()
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    params = {"wte": jnp.asarray(_np(sd[f"{pfx}wte.weight"])),
              "wpe": jnp.asarray(_np(sd[f"{pfx}wpe.weight"])),
              "ln_f": _ln(sd, f"{pfx}ln_f")}
    for i in range(cfg.n_layer):
        lp = f"{pfx}h.{i}"
        # HF GPT-2 uses Conv1D: weight already (in, out)
        qkv = _split_fused_qkv(sd[f"{lp}.attn.c_attn.weight"],
                               sd.get(f"{lp}.attn.c_attn.bias"),
                               cfg.n_head, cfg.head_dim, interleaved=False)
        params[f"layers_{i}"] = {
            "ln_attn": _ln(sd, f"{lp}.ln_1"),
            "ln_mlp": _ln(sd, f"{lp}.ln_2"),
            "q_proj": qkv[0], "k_proj": qkv[1], "v_proj": qkv[2],
            "o_proj": {"kernel": jnp.asarray(_np(sd[f"{lp}.attn.c_proj.weight"])),
                       "bias": _vec(sd[f"{lp}.attn.c_proj.bias"])},
            "fc_in": {"kernel": jnp.asarray(_np(sd[f"{lp}.mlp.c_fc.weight"])),
                      "bias": _vec(sd[f"{lp}.mlp.c_fc.bias"])},
            "fc_out": {"kernel": jnp.asarray(_np(sd[f"{lp}.mlp.c_proj.weight"])),
                       "bias": _vec(sd[f"{lp}.mlp.c_proj.bias"])},
        }
    return cfg, params


def _convert_gptneo(model) -> Tuple[CausalLMConfig, Any]:
    """GPT-Neo (reference container ``module_inject/containers/gptneo.py:1``):
    GPT-2-style learned positions with SEPARATE bias-free q/k/v projections
    (torch ``nn.Linear``, not Conv1D — kernels transpose) and alternating
    global/LOCAL attention. A local layer attends to the trailing
    ``window_size`` tokens, which coincides with causal attention inside the
    window, so ``max_seq_len`` is clamped to the window (the local-attention
    layout trap; same treatment as the Mistral sliding-window clamp)."""
    hf = model.config
    max_len = hf.max_position_embeddings
    if "local" in getattr(hf, "attention_layers", []):
        window = int(hf.window_size)
        if max_len > window:
            logger.warning(
                f"gpt-neo uses local attention with window {window}: serving "
                f"clamps max_seq_len {max_len} -> {window} (beyond the window "
                "local and causal attention diverge)")
        max_len = min(max_len, window)
    cfg = gpt2_cfg(vocab_size=hf.vocab_size, max_seq_len=max_len,
                   n_embd=hf.hidden_size, n_layer=hf.num_layers,
                   n_head=hf.num_heads,
                   d_ff=hf.intermediate_size or 4 * hf.hidden_size,
                   ln_eps=hf.layer_norm_epsilon, qkv_bias=False)
    cfg.name = "gptneo"
    act_map = {"gelu_new": "gelu", "gelu": "gelu", "gelu_fast": "gelu",
               "gelu_pytorch_tanh": "gelu", "relu": "relu"}
    act = getattr(hf, "activation_function", "gelu_new")
    if act not in act_map:
        raise ValueError(
            f"gpt-neo activation_function={act!r} has no CausalLM equivalent "
            f"(supported: {sorted(act_map)})")
    cfg.activation = act_map[act]
    sd = model.state_dict()
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    params = {"wte": jnp.asarray(_np(sd[f"{pfx}wte.weight"])),
              "wpe": jnp.asarray(_np(sd[f"{pfx}wpe.weight"])[:max_len]),
              "ln_f": _ln(sd, f"{pfx}ln_f")}
    if not getattr(hf, "tie_word_embeddings", True) and "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": _kernel(sd["lm_head.weight"])}
        cfg.tie_word_embeddings = False
    for i in range(cfg.n_layer):
        lp = f"{pfx}h.{i}"
        ap = f"{lp}.attn.attention"
        params[f"layers_{i}"] = {
            "ln_attn": _ln(sd, f"{lp}.ln_1"),
            "ln_mlp": _ln(sd, f"{lp}.ln_2"),
            # GPT-Neo applies NO 1/sqrt(d_head) attention scaling; folding
            # sqrt(d_head) into the q kernel cancels this model's scaling exactly
            "q_proj": {"kernel": _kernel(sd[f"{ap}.q_proj.weight"])
                       * float(np.sqrt(cfg.head_dim))},
            "k_proj": {"kernel": _kernel(sd[f"{ap}.k_proj.weight"])},
            "v_proj": {"kernel": _kernel(sd[f"{ap}.v_proj.weight"])},
            "o_proj": {"kernel": _kernel(sd[f"{ap}.out_proj.weight"]),
                       "bias": _vec(sd[f"{ap}.out_proj.bias"])},
            "fc_in": {"kernel": _kernel(sd[f"{lp}.mlp.c_fc.weight"]),
                      "bias": _vec(sd[f"{lp}.mlp.c_fc.bias"])},
            "fc_out": {"kernel": _kernel(sd[f"{lp}.mlp.c_proj.weight"]),
                       "bias": _vec(sd[f"{lp}.mlp.c_proj.bias"])},
        }
    return cfg, params


def _convert_bloom(model) -> Tuple[CausalLMConfig, Any]:
    hf = model.config
    cfg = bloom_cfg(vocab_size=hf.vocab_size, max_seq_len=2048,
                    n_embd=hf.hidden_size, n_layer=hf.n_layer, n_head=hf.n_head,
                    ln_eps=hf.layer_norm_epsilon)
    sd = model.state_dict()
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    params = {"wte": jnp.asarray(_np(sd[f"{pfx}word_embeddings.weight"])),
              "ln_embed": _ln(sd, f"{pfx}word_embeddings_layernorm"),
              "ln_f": _ln(sd, f"{pfx}ln_f")}
    for i in range(cfg.n_layer):
        lp = f"{pfx}h.{i}"
        qkv = _split_fused_qkv(sd[f"{lp}.self_attention.query_key_value.weight"],
                               sd.get(f"{lp}.self_attention.query_key_value.bias"),
                               cfg.n_head, cfg.head_dim, interleaved=True)
        params[f"layers_{i}"] = {
            "ln_attn": _ln(sd, f"{lp}.input_layernorm"),
            "ln_mlp": _ln(sd, f"{lp}.post_attention_layernorm"),
            "q_proj": qkv[0], "k_proj": qkv[1], "v_proj": qkv[2],
            "o_proj": {"kernel": _kernel(sd[f"{lp}.self_attention.dense.weight"]),
                       "bias": _vec(sd[f"{lp}.self_attention.dense.bias"])},
            "fc_in": {"kernel": _kernel(sd[f"{lp}.mlp.dense_h_to_4h.weight"]),
                      "bias": _vec(sd[f"{lp}.mlp.dense_h_to_4h.bias"])},
            "fc_out": {"kernel": _kernel(sd[f"{lp}.mlp.dense_4h_to_h.weight"]),
                       "bias": _vec(sd[f"{lp}.mlp.dense_4h_to_h.bias"])},
        }
    return cfg, params


def _convert_opt(model) -> Tuple[CausalLMConfig, Any]:
    hf = model.config
    # OPT variants this converter does not model: 350m's project_in/project_out
    # (word_embed_proj_dim != hidden_size) and 125m/350m post-LN — fail loudly instead of
    # converting to a silently wrong model.
    if getattr(hf, "word_embed_proj_dim", hf.hidden_size) != hf.hidden_size:
        raise NotImplementedError(
            "OPT variants with word_embed_proj_dim != hidden_size (e.g. opt-350m) are not "
            "supported")
    if not getattr(hf, "do_layer_norm_before", True):
        raise NotImplementedError(
            "post-layernorm OPT variants (do_layer_norm_before=False) are not supported")
    cfg = opt_cfg(vocab_size=hf.vocab_size, max_seq_len=hf.max_position_embeddings,
                  n_embd=hf.hidden_size, n_layer=hf.num_hidden_layers,
                  n_head=hf.num_attention_heads, d_ff=hf.ffn_dim,
                  tie_word_embeddings=getattr(hf, "tie_word_embeddings", True))
    sd = model.state_dict()
    pfx = next((p for p in ("model.decoder.", "decoder.", "")
                if f"{p}embed_tokens.weight" in sd), "")
    # OPT offsets learned positions by 2
    wpe = _np(sd[f"{pfx}embed_positions.weight"])[2:]
    params = {"wte": jnp.asarray(_np(sd[f"{pfx}embed_tokens.weight"])),
              "wpe": jnp.asarray(wpe),
              "ln_f": _ln(sd, f"{pfx}final_layer_norm")}
    for i in range(cfg.n_layer):
        lp = f"{pfx}layers.{i}"
        params[f"layers_{i}"] = {
            "ln_attn": _ln(sd, f"{lp}.self_attn_layer_norm"),
            "ln_mlp": _ln(sd, f"{lp}.final_layer_norm"),
            "q_proj": {"kernel": _kernel(sd[f"{lp}.self_attn.q_proj.weight"]),
                       "bias": _vec(sd[f"{lp}.self_attn.q_proj.bias"])},
            "k_proj": {"kernel": _kernel(sd[f"{lp}.self_attn.k_proj.weight"]),
                       "bias": _vec(sd[f"{lp}.self_attn.k_proj.bias"])},
            "v_proj": {"kernel": _kernel(sd[f"{lp}.self_attn.v_proj.weight"]),
                       "bias": _vec(sd[f"{lp}.self_attn.v_proj.bias"])},
            "o_proj": {"kernel": _kernel(sd[f"{lp}.self_attn.out_proj.weight"]),
                       "bias": _vec(sd[f"{lp}.self_attn.out_proj.bias"])},
            "fc_in": {"kernel": _kernel(sd[f"{lp}.fc1.weight"]),
                      "bias": _vec(sd[f"{lp}.fc1.bias"])},
            "fc_out": {"kernel": _kernel(sd[f"{lp}.fc2.weight"]),
                       "bias": _vec(sd[f"{lp}.fc2.bias"])},
        }
    return cfg, params


def _convert_llama(model, qkv_bias: bool = False,
                   name: str = "llama") -> Tuple[CausalLMConfig, Any]:
    """LLaMA-family layout walk, shared by llama/mistral/qwen2 (which differ only in
    qkv biases, name, and window clamping)."""
    hf = model.config
    cfg = llama_cfg(vocab_size=hf.vocab_size, max_seq_len=hf.max_position_embeddings,
                    n_embd=hf.hidden_size, n_layer=hf.num_hidden_layers,
                    n_head=hf.num_attention_heads,
                    n_kv_head=getattr(hf, "num_key_value_heads", None),
                    d_ff=hf.intermediate_size, ln_eps=hf.rms_norm_eps,
                    rotary_base=getattr(hf, "rope_theta", 10000.0),
                    qkv_bias=qkv_bias, name=name)
    sd = model.state_dict()
    pfx = "model." if any(k.startswith("model.") for k in sd) else ""
    params = {"wte": jnp.asarray(_np(sd[f"{pfx}embed_tokens.weight"])),
              "ln_f": {"scale": _vec(sd[f"{pfx}norm.weight"])}}
    if "lm_head.weight" in sd:
        params["lm_head"] = {"kernel": _kernel(sd["lm_head.weight"])}
    else:
        cfg.tie_word_embeddings = True  # checkpoint ties the head to wte

    def proj(path, with_bias):
        out = {"kernel": _kernel(sd[f"{path}.weight"])}
        if with_bias:
            out["bias"] = _vec(sd[f"{path}.bias"])
        return out

    for i in range(cfg.n_layer):
        lp = f"{pfx}layers.{i}"
        params[f"layers_{i}"] = {
            "ln_attn": {"scale": _vec(sd[f"{lp}.input_layernorm.weight"])},
            "ln_mlp": {"scale": _vec(sd[f"{lp}.post_attention_layernorm.weight"])},
            "q_proj": proj(f"{lp}.self_attn.q_proj", qkv_bias),
            "k_proj": proj(f"{lp}.self_attn.k_proj", qkv_bias),
            "v_proj": proj(f"{lp}.self_attn.v_proj", qkv_bias),
            "o_proj": {"kernel": _kernel(sd[f"{lp}.self_attn.o_proj.weight"])},
            "gate_proj": {"kernel": _kernel(sd[f"{lp}.mlp.gate_proj.weight"])},
            "up_proj": {"kernel": _kernel(sd[f"{lp}.mlp.up_proj.weight"])},
            "fc_out": {"kernel": _kernel(sd[f"{lp}.mlp.down_proj.weight"])},
        }
    return cfg, params


def _convert_gptneox(model) -> Tuple[CausalLMConfig, Any]:
    hf = model.config
    cfg = gptneox_cfg(vocab_size=hf.vocab_size, max_seq_len=hf.max_position_embeddings,
                      n_embd=hf.hidden_size, n_layer=hf.num_hidden_layers,
                      n_head=hf.num_attention_heads, d_ff=hf.intermediate_size,
                      rotary_pct=hf.rotary_pct, rotary_base=hf.rotary_emb_base,
                      ln_eps=hf.layer_norm_eps)
    sd = model.state_dict()
    pfx = "gpt_neox." if any(k.startswith("gpt_neox.") for k in sd) else ""
    params = {"wte": jnp.asarray(_np(sd[f"{pfx}embed_in.weight"])),
              "ln_f": _ln(sd, f"{pfx}final_layer_norm")}
    if "embed_out.weight" in sd:
        params["lm_head"] = {"kernel": _kernel(sd["embed_out.weight"])}
    for i in range(cfg.n_layer):
        lp = f"{pfx}layers.{i}"
        qkv = _split_fused_qkv(sd[f"{lp}.attention.query_key_value.weight"],
                               sd.get(f"{lp}.attention.query_key_value.bias"),
                               cfg.n_head, cfg.head_dim, interleaved=True)
        params[f"layers_{i}"] = {
            "ln_attn": _ln(sd, f"{lp}.input_layernorm"),
            "ln_mlp": _ln(sd, f"{lp}.post_attention_layernorm"),
            "q_proj": qkv[0], "k_proj": qkv[1], "v_proj": qkv[2],
            "o_proj": {"kernel": _kernel(sd[f"{lp}.attention.dense.weight"]),
                       "bias": _vec(sd[f"{lp}.attention.dense.bias"])},
            "fc_in": {"kernel": _kernel(sd[f"{lp}.mlp.dense_h_to_4h.weight"]),
                      "bias": _vec(sd[f"{lp}.mlp.dense_h_to_4h.bias"])},
            "fc_out": {"kernel": _kernel(sd[f"{lp}.mlp.dense_4h_to_h.weight"]),
                       "bias": _vec(sd[f"{lp}.mlp.dense_4h_to_h.bias"])},
        }
    return cfg, params


def _rotary_interleaved_to_half(kernel, bias, n_head: int, head_dim: int,
                                rotary_dim: int):
    """Re-order q/k projection outputs from GPT-J's INTERLEAVED rotary pairing
    ((2i, 2i+1) per frequency) to this model's NeoX half-split pairing
    ((i, i + rot/2)). Permuting q and k identically leaves attention scores
    invariant, and NeoX rotary on the permuted layout equals the permutation of
    GPT-J rotary on the original — the standard GPT-J → NeoX weight conversion."""
    perm_head = np.arange(head_dim)
    half = rotary_dim // 2
    perm_head[:half] = np.arange(0, rotary_dim, 2)
    perm_head[half:rotary_dim] = np.arange(1, rotary_dim, 2)
    perm = np.concatenate([h * head_dim + perm_head for h in range(n_head)])
    out = {"kernel": kernel[:, perm]}
    if bias is not None:
        out["bias"] = bias[perm]
    return out


def _convert_gptj(model) -> Tuple[CausalLMConfig, Any]:
    """GPT-J (reference container ``module_inject/containers/gptj.py``): parallel
    residual with ONE shared layernorm, partial interleaved rotary, biasless
    q/k/v/out, biased mlp + lm_head."""
    hf = model.config
    head_dim = hf.n_embd // hf.n_head
    cfg = gptj_cfg(vocab_size=hf.vocab_size, max_seq_len=hf.n_positions,
                   n_embd=hf.n_embd, n_layer=hf.n_layer, n_head=hf.n_head,
                   d_ff=hf.n_inner or 4 * hf.n_embd,
                   rotary_pct=hf.rotary_dim / head_dim,
                   ln_eps=hf.layer_norm_epsilon,
                   qkv_bias=False, tie_word_embeddings=False, lm_head_bias=True)
    sd = model.state_dict()
    pfx = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    params = {"wte": jnp.asarray(_np(sd[f"{pfx}wte.weight"])),
              "ln_f": _ln(sd, f"{pfx}ln_f"),
              "lm_head": {"kernel": _kernel(sd["lm_head.weight"]),
                          "bias": _vec(sd["lm_head.bias"])}}
    zero_o_bias = jnp.zeros((cfg.n_embd,), jnp.float32)
    for i in range(cfg.n_layer):
        lp = f"{pfx}h.{i}"
        shared_ln = _ln(sd, f"{lp}.ln_1")
        q = _rotary_interleaved_to_half(
            _kernel(sd[f"{lp}.attn.q_proj.weight"]), None,
            cfg.n_head, head_dim, hf.rotary_dim)
        k = _rotary_interleaved_to_half(
            _kernel(sd[f"{lp}.attn.k_proj.weight"]), None,
            cfg.n_head, head_dim, hf.rotary_dim)
        params[f"layers_{i}"] = {
            # GPT-J shares one LN across the parallel branches; duplicating it into
            # the two-LN parallel-residual block is numerically identical
            "ln_attn": shared_ln, "ln_mlp": shared_ln,
            "q_proj": q, "k_proj": k,
            "v_proj": {"kernel": _kernel(sd[f"{lp}.attn.v_proj.weight"])},
            # out_proj is biasless in GPT-J but the block's o_proj follows mlp_bias:
            # a zero bias is exact
            "o_proj": {"kernel": _kernel(sd[f"{lp}.attn.out_proj.weight"]),
                       "bias": zero_o_bias},
            "fc_in": {"kernel": _kernel(sd[f"{lp}.mlp.fc_in.weight"]),
                      "bias": _vec(sd[f"{lp}.mlp.fc_in.bias"])},
            "fc_out": {"kernel": _kernel(sd[f"{lp}.mlp.fc_out.weight"]),
                       "bias": _vec(sd[f"{lp}.mlp.fc_out.bias"])},
        }
    return cfg, params


def _convert_mistral(model) -> Tuple[CausalLMConfig, Any]:
    """Mistral (reference container ``containers/llama.py`` family): identical param
    layout to LLaMA; sliding-window attention is clamped by limiting max_seq_len to
    the window (within it the semantics coincide)."""
    cfg, params = _convert_llama(model)
    window = getattr(model.config, "sliding_window", None)
    if window:
        if cfg.max_seq_len > window:
            logger.warning(f"mistral: clamping max_seq_len {cfg.max_seq_len} -> "
                           f"sliding_window {window} (windowed attention beyond it "
                           "is not implemented)")
        cfg.max_seq_len = min(cfg.max_seq_len, window)
    cfg.name = "mistral"
    return cfg, params


def _convert_qwen2(model) -> Tuple[CausalLMConfig, Any]:
    """Qwen2 (``containers/`` llama family): LLaMA layout + biases on q/k/v only."""
    return _convert_llama(model, qkv_bias=True, name="qwen2")


HF_POLICIES: Dict[str, Callable] = {
    "gpt2": _convert_gpt2,
    "gpt_neo": _convert_gptneo,
    "bloom": _convert_bloom,
    "opt": _convert_opt,
    "llama": _convert_llama,
    "gpt_neox": _convert_gptneox,
    "gptj": _convert_gptj,
    "mistral": _convert_mistral,
    "qwen2": _convert_qwen2,
}


def convert_hf_model(model) -> Tuple[CausalLMConfig, Any]:
    """Convert an HF torch CausalLM into (CausalLMConfig, jax params).

    Reference ``replace_transformer_layer``'s ``policy`` selection, resolved by
    ``config.model_type`` (the reference's auto ``replace_method``)."""
    model_type = getattr(getattr(model, "config", None), "model_type", None)
    if model_type not in HF_POLICIES:
        # generic fallback (reference auto_tp.py AutoTP): classify the architecture
        # by parameter-name conventions; raises with the failing census when the
        # model does not fit the CausalLM knob space
        from .auto_tp import auto_convert_hf_model
        logger.info(f"no named policy for model_type={model_type!r}; "
                    f"trying the auto-TP generic policy")
        return auto_convert_hf_model(model)
    logger.info(f"converting HF {model_type} model to TPU-native CausalLM")
    return HF_POLICIES[model_type](model)


def convert_training_model(train_cfg, params) -> Tuple[CausalLMConfig, Any]:
    """Convert OUR training models' param trees (GPT2 / GPT2MoE, ``models/gpt2*.py``) into
    the :class:`CausalLM` serving tree — the in-framework analogue of the reference's
    Megatron state-dict loader (``runtime/state_dict_factory.py:214``): train, checkpoint,
    then serve through the inference engine with KV caches.

    Handles both scan-stacked (``h`` with leading layer dim) and unstacked (``h_{i}`` /
    ``h_moe_{i}``) layouts.
    """
    import jax

    num_experts = int(getattr(train_cfg, "num_experts", 0) or 0)
    cfg = gpt2_cfg(vocab_size=train_cfg.vocab_size, max_seq_len=train_cfg.n_positions,
                   n_embd=train_cfg.n_embd, n_layer=train_cfg.n_layer,
                   n_head=train_cfg.n_head, num_experts=num_experts,
                   moe_layer_interval=getattr(train_cfg, "moe_layer_interval", 2),
                   moe_top_k=getattr(train_cfg, "top_k", 1))
    params = jax.tree_util.tree_map(np.asarray, params)

    def dense_layer(blk):
        qkv_k = np.split(np.asarray(blk["c_attn"]["kernel"]), 3, axis=1)
        qkv_b = np.split(np.asarray(blk["c_attn"]["bias"]), 3, axis=0)
        return {
            "ln_attn": blk["ln_1"], "ln_mlp": blk["ln_2"],
            "q_proj": {"kernel": qkv_k[0], "bias": qkv_b[0]},
            "k_proj": {"kernel": qkv_k[1], "bias": qkv_b[1]},
            "v_proj": {"kernel": qkv_k[2], "bias": qkv_b[2]},
            "o_proj": blk["c_proj"],
            "fc_in": blk["c_fc"],
            "fc_out": blk["mlp_c_proj"],
        }

    def moe_layer(blk):
        if "residual_fc1" in blk.get("moe", {}):
            raise NotImplementedError("residual-MoE serving is not supported")
        qkv_k = np.split(np.asarray(blk["c_attn"]["kernel"]), 3, axis=1)
        qkv_b = np.split(np.asarray(blk["c_attn"]["bias"]), 3, axis=0)
        return {
            "ln_attn": blk["ln_1"], "ln_mlp": blk["ln_2"],
            "q_proj": {"kernel": qkv_k[0], "bias": qkv_b[0]},
            "k_proj": {"kernel": qkv_k[1], "bias": qkv_b[1]},
            "v_proj": {"kernel": qkv_k[2], "bias": qkv_b[2]},
            "o_proj": blk["c_proj"],
            "moe_gate": blk["moe"]["gate_wg"],
            "moe_experts": blk["moe"]["experts"],
        }

    new = {"wte": params["wte"], "wpe": params["wpe"], "ln_f": params["ln_f"]}
    if "h" in params:  # scan-stacked homogeneous body
        stacked = params["h"]
        for i in range(cfg.n_layer):
            blk = jax.tree_util.tree_map(lambda x: x[i], stacked)
            new[f"layers_{i}"] = dense_layer(blk)
    else:
        for i in range(cfg.n_layer):
            if f"h_moe_{i}" in params:
                new[f"layers_{i}"] = moe_layer(params[f"h_moe_{i}"])
            elif f"h_{i}" in params:
                new[f"layers_{i}"] = dense_layer(params[f"h_{i}"])
            else:
                raise KeyError(f"layer {i} not found in training params "
                               f"(expected 'h', 'h_{i}' or 'h_moe_{i}')")
    new = jax.tree_util.tree_map(jnp.asarray, new)
    return cfg, new


def replace_transformer_layer(orig_layer_impl, model, checkpoint=None, config=None,
                              **kwargs):
    """Reference-named API shim (``replace_module.py:308``): returns the converted
    (config, params) pair — on TPU 'replacement' is conversion + sharded placement."""
    return convert_hf_model(model)
