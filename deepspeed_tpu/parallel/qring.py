"""Fused quantized collective-matmul ring — dequant-GEMM inside the ppermute
ring with an intN + error-feedback wire payload.

PR 5's Pallas dequant-matmul (``ops/quantizer/fused_matmul.py``) and PR 3's
chunked collective matmuls (``parallel/overlap.py``) deliberately did not
compose: quantized row-parallel was monolithic-psum only, so TP decode over
quantized weights paid full fp wire time with zero compute/comm overlap. This
module is the composition (the fused computation-collective idiom of arXiv
2305.06942 with EQuARX, arXiv 2506.17615, as the quantized-wire precedent):

- the per-chunk GEMM is the fused dequant-matmul over the shard's WHOLE
  packed weight slab (int8 or nibble-packed int4, per-group scales sharded
  with their k rows, so each rank dequants locally — group boundaries never
  cross the wire; only fp accumulator chunks do, which is why the ring can
  now re-slice freely);
- the ring payload itself is quantized: intN chunks (``chunk_bits`` in
  {4, 8, 16}) with per-block absmax scales, under the same error-feedback
  contract as ``comm/compressed.py`` — ``transmitted + new_error == chunk +
  error`` exactly per hop, non-finite values zeroed BEFORE the cast
  (overflow-gated), residual carried ACROSS ring steps within a dispatch.

EF residual lifecycle in serving: a decode dispatch is ONE transmission, so
:func:`quant_row_parallel_apply` starts every dispatch from a zero residual
and discards the returned one — the "residual reset on load" contract of the
DP gradient sync is therefore satisfied trivially (``load_checkpoint`` →
``_place_params`` re-quantizes; no stale wire state can survive it), and
bit-exact request retry (the serving contract) is preserved because no state
leaks between dispatches. Callers that DO iterate transmissions (the EF
convergence smoke in ``tests/unit/parallel/test_qring.py``) thread
``residual`` through repeated calls and get the cumulative-transmission EF
guarantee back.

Wire-bytes model (per worker, one dispatch; cross-checked exactly by the
``analysis/collectives.py`` schema pass — the recorded span, the closed form
:func:`analysis.collectives.qring_wire_bytes`, and the jaxpr ppermute-operand
sum must all agree to the byte):

    hops x intn_wire_nbytes(m_blk * n_dir, quant_block, chunk_bits)

with ``m_blk = m / W`` rows per ring chunk, ``n_dir = n`` (unidirectional,
``W - 1`` hops) or ``n / 2`` (bidirectional, ``2 (W - 1)`` half-width hops).
At tp=4 / int8 wire / block=256 that is ~0.25x the fp32 ring's bytes.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..comm.compressed import (intn_blockwise_compress,
                               intn_blockwise_decompress, intn_wire_nbytes)
from ..utils.comms_logging import record_collective
from ..utils.jax_compat import shard_map
from .mesh import AXIS_TENSOR, get_global_mesh
from .overlap import OverlapConfig, _ring_perm, _scoped


def _wire_hop(chunk, residual, axis_name, perm, wire_bits: Optional[int],
              block: int):
    """One quantized ring hop: EF-compress the fp accumulator chunk, ship
    carrier + scales, decompress on arrival. Returns ``(received fp chunk,
    new residual)``; ``wire_bits=None`` is the fp (lossless) wire used for
    exact ground-truthing."""
    if wire_bits is None:
        return jax.lax.ppermute(chunk, axis_name, perm), residual
    flat = chunk.reshape(-1) + residual
    # overflow gate (same contract as comm/compressed.py): a single inf/nan
    # must not poison the intN cast or the residual — it is zeroed on the
    # wire, and the caller's own (never-wired) partial keeps local semantics
    flat = jnp.where(jnp.isfinite(flat), flat, 0.0)
    payload, scales = intn_blockwise_compress(flat, block, wire_bits)
    new_residual = flat - intn_blockwise_decompress(
        payload, scales, flat.shape[0], block, wire_bits)
    payload = jax.lax.ppermute(payload, axis_name, perm)
    scales = jax.lax.ppermute(scales, axis_name, perm)
    received = intn_blockwise_decompress(
        payload, scales, flat.shape[0], block, wire_bits)
    return received.reshape(chunk.shape), new_residual


def _chunk_gemm(x, q, scales, bits: int, groups: int, m_blk: int,
                interpret: Optional[bool]):
    """Per-ring-chunk GEMM closure over one (column slice of a) quant slab.

    Fused backend: the Pallas dequant-matmul streams the packed slab per
    chunk. Otherwise the per-group dequant is hoisted HERE, once per trace,
    OUTSIDE the ring steps — the loop-invariance contract the qring lint
    lane pins (a per-step dequant would re-materialise the fp weight W
    times and regrow the hot-path HBM read the quant store exists to
    shrink)."""
    from ..ops.quantizer.fused_matmul import (_block_config, _interpret,
                                              fused_backend_active,
                                              quantized_matmul)
    from ..ops.quantizer.quant import dequantize_grouped, unpack_int4
    k = x.shape[1]
    n = scales.shape[-1]
    interp = _interpret() if interpret is None else interpret
    group = k // groups
    if fused_backend_active() and \
            _block_config(m_blk, k, n, bits, group, interp) is not None:
        def gemm(rows):
            return quantized_matmul(rows, q, scales, bits=bits,
                                    out_dtype=jnp.float32, interpret=interp)
        return gemm
    w = dequantize_grouped(unpack_int4(q, groups) if bits == 4 else q, scales)

    def gemm(rows):
        return jnp.dot(rows.astype(jnp.float32), w,
                       preferred_element_type=jnp.float32)
    return gemm


@_scoped("comm.fused_quant_matmul_reduce_scatter")
def fused_quant_matmul_reduce_scatter(x, q, scales, axis_name, *,
                                      bits: int = 8,
                                      wire_bits: Optional[int] = 8,
                                      quant_block: int = 256,
                                      bidirectional: bool = True,
                                      residual=None, interpret=None,
                                      site=None) -> Tuple[jnp.ndarray,
                                                          jnp.ndarray]:
    """``psum_scatter(x @ dequant(q, scales), dim 0, tiled)`` as a
    dequant-GEMM / accumulate ring with a quantized wire payload.

    ``x``: ``(m, k_loc)`` local activation slice (``m`` divisible by the axis
    size); ``q``/``scales``: THIS shard's weight slab (int8 ``(k_loc, n)`` or
    packed int4 ``(k_loc/2, n)``; f32 ``(k_loc/group, n)``). Returns
    ``(out (m/W, n) f32, new_residual (m/W * n,) f32)``.

    Ring structure mirrors ``overlap.chunked_matmul_reduce_scatter`` (each
    ICI hop hides under the next block's dequant-GEMM); each hop additionally
    EF-quantizes the travelling accumulator via :func:`_wire_hop`. The
    residual a rank carries follows its SEND slot across the W-1 steps (EF
    across ring steps); pass ``residual`` to chain dispatches, or None for
    the serving fresh-per-dispatch contract. ``wire_bits=None`` keeps the
    wire fp (bit-identical hops; last-ulp vs the monolithic psum, summation
    order only).
    """
    W = jax.lax.psum(1, axis_name)
    m, k = x.shape
    groups, n = scales.shape[-2], scales.shape[-1]
    if W == 1:
        gemm = _chunk_gemm(x, q, scales, bits, groups, m, interpret)
        res = residual if residual is not None \
            else jnp.zeros((m * n,), jnp.float32)
        return gemm(x), res
    if m % W != 0:
        # must survive python -O: dynamic_slice CLAMPS out-of-range block
        # starts, so an unguarded ragged m would silently double-sum rows
        raise ValueError(
            f"fused_quant_matmul_reduce_scatter: m={m} not divisible by "
            f"axis size {W} — pad rows first (see quant_row_parallel_apply)")
    idx = jax.lax.axis_index(axis_name)
    m_blk = m // W
    if residual is None:
        residual = jnp.zeros((m_blk * n,), jnp.float32)
    bidir = bidirectional and n % 2 == 0
    n_dir = n // 2 if bidir else n
    hop_bytes = (m_blk * n_dir * 4 if wire_bits is None
                 else intn_wire_nbytes(m_blk * n_dir, quant_block, wire_bits))
    if site is not None:
        record_collective(site, "reduce_scatter",
                          (W - 1) * (2 if bidir else 1) * hop_bytes, W,
                          overlapped=True)

    def rows(b):
        return jax.lax.dynamic_slice(x, (b * m_blk, 0), (m_blk, k))

    if not bidir:
        gemm = _chunk_gemm(x, q, scales, bits, groups, m_blk, interpret)
        perm = _ring_perm(W, 1)
        acc = gemm(rows((idx - 1) % W))
        r = residual
        for s in range(1, W):
            acc, r = _wire_hop(acc, r, axis_name, perm, wire_bits, quant_block)
            acc = acc + gemm(rows((idx - 1 - s) % W))
        return acc, r

    # bidirectional: column halves travel opposite ring directions (both ICI
    # links busy at half the per-step payload); the packed int4 layout splits
    # cleanly on n — packing is along k, so no group is re-sliced
    h = n // 2
    hq = q.shape[-1] // 2
    gemm_a = _chunk_gemm(x, q[:, :hq], scales[:, :h], bits, groups, m_blk,
                         interpret)
    gemm_b = _chunk_gemm(x, q[:, hq:], scales[:, h:], bits, groups, m_blk,
                         interpret)
    r_a, r_b = residual[:m_blk * h], residual[m_blk * h:]
    perm_f, perm_b = _ring_perm(W, 1), _ring_perm(W, -1)
    acc_a = gemm_a(rows((idx - 1) % W))
    acc_b = gemm_b(rows((idx + 1) % W))
    for s in range(1, W):
        acc_a, r_a = _wire_hop(acc_a, r_a, axis_name, perm_f, wire_bits,
                               quant_block)
        acc_a = acc_a + gemm_a(rows((idx - 1 - s) % W))
        acc_b, r_b = _wire_hop(acc_b, r_b, axis_name, perm_b, wire_bits,
                               quant_block)
        acc_b = acc_b + gemm_b(rows((idx + 1 + s) % W))
    return jnp.concatenate([acc_a, acc_b], axis=1), \
        jnp.concatenate([r_a, r_b])


@_scoped("comm.fused_quant_allgather_matmul")
def fused_quant_allgather_matmul(x, q, scales, axis_name, *, bits: int = 8,
                                 wire_bits: Optional[int] = 8,
                                 quant_block: int = 256,
                                 bidirectional: bool = True, residual=None,
                                 interpret=None, site=None
                                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``all_gather(x, axis=0, tiled) @ dequant(q, scales)`` as a ppermute
    ring with a quantized activation payload.

    ``x``: ``(m_loc, k)`` row block; ``q``/``scales``: the LOCAL column
    slice of the quant slab. Returns ``((W*m_loc, n_loc) f32,
    new_residual (m_loc*k,) f32)``.

    Unlike the reduce-scatter ring (whose accumulator changes at every hop
    and must be re-quantized), each origin's chunk here is compressed ONCE and the
    CARRIER is forwarded verbatim — quantization error is one-shot per
    origin, never compounded per hop, and every rank (the origin included)
    GEMMs the dequantized chunk so the replicated output stays identical
    across ranks. EF applies at the origin's single compression.
    """
    W = jax.lax.psum(1, axis_name)
    m_loc, k = x.shape
    groups, n = scales.shape[-2], scales.shape[-1]
    if residual is None:
        residual = jnp.zeros((m_loc * k,), jnp.float32)
    gemm = _chunk_gemm(x, q, scales, bits, groups, m_loc, interpret)
    if W == 1:
        return gemm(x.astype(jnp.float32)), residual
    hop_bytes = (m_loc * k * 4 if wire_bits is None
                 else intn_wire_nbytes(m_loc * k, quant_block, wire_bits))
    if site is not None:
        # W-1 full-chunk hops total whichever direction split is used
        record_collective(site, "all_gather", (W - 1) * hop_bytes, W,
                          overlapped=True)
    idx = jax.lax.axis_index(axis_name)
    out = jnp.zeros((W * m_loc, n), jnp.float32)

    def write(out, block, src):
        return jax.lax.dynamic_update_slice(out, gemm(block), (src * m_loc, 0))

    if wire_bits is None:
        xf = x.astype(jnp.float32)
        if not bidirectional:
            cur = xf
            for s in range(W):
                out = write(out, cur, (idx - s) % W)
                if s != W - 1:
                    cur = jax.lax.ppermute(cur, axis_name, _ring_perm(W, 1))
            return out, residual
        fwd = bwd = xf
        out = write(out, xf, idx)
        for s in range(1, W // 2 + 1):
            fwd = jax.lax.ppermute(fwd, axis_name, _ring_perm(W, 1))
            out = write(out, fwd, (idx - s) % W)
            if s <= (W - 1) // 2:
                bwd = jax.lax.ppermute(bwd, axis_name, _ring_perm(W, -1))
                out = write(out, bwd, (idx + s) % W)
        return out, residual

    flat = x.reshape(-1).astype(jnp.float32) + residual
    flat = jnp.where(jnp.isfinite(flat), flat, 0.0)
    payload, pscales = intn_blockwise_compress(flat, quant_block, wire_bits)
    own = intn_blockwise_decompress(payload, pscales, m_loc * k, quant_block,
                                    wire_bits)
    new_residual = flat - own
    own = own.reshape(m_loc, k)

    def hop(carrier, step):
        p, sc = carrier
        perm = _ring_perm(W, step)
        p = jax.lax.ppermute(p, axis_name, perm)
        sc = jax.lax.ppermute(sc, axis_name, perm)
        blk = intn_blockwise_decompress(p, sc, m_loc * k, quant_block,
                                        wire_bits).reshape(m_loc, k)
        return (p, sc), blk

    out = write(out, own, idx)
    if not bidirectional:
        cur = (payload, pscales)
        for s in range(1, W):
            cur, blk = hop(cur, 1)
            out = write(out, blk, (idx - s) % W)
        return out, new_residual
    fwd = bwd = (payload, pscales)
    for s in range(1, W // 2 + 1):
        fwd, blk = hop(fwd, 1)
        out = write(out, blk, (idx - s) % W)
        if s <= (W - 1) // 2:
            bwd, blk = hop(bwd, -1)
            out = write(out, blk, (idx + s) % W)
    return out, new_residual


# ------------------------------------------- GSPMD-callable serving wrapper
def quant_row_parallel_apply(x, q, scales, *, bits: int, dtype,
                             mesh, batch_axes, cfg: OverlapConfig,
                             interpret=None, site: str = "tp.row_dense"):
    """Quantized row-parallel dense through the fused quantized ring — the
    quant-node analogue of ``overlap.row_parallel_dense_apply`` (same row
    padding, same ``site``/``site + ".gather"`` span convention, so bench
    A/Bs line up column-for-column).

    The ring's wire width and scale block come from the engine's
    ``comm_overlap`` config (``chunk_bits``/``quant_block``); the EF residual
    is freshly zero each dispatch and the returned one discarded (see module
    docstring for why serving resets rather than persists it). Bias handling
    stays with the caller (``quant_dense_apply``)."""
    b, t, k = x.shape
    n = scales.shape[-1]
    tp = mesh.size(AXIS_TENSOR)
    bsz = int(np.prod([mesh.size(ax) for ax in batch_axes])) if batch_axes \
        else 1
    m_loc = (b // bsz) * t
    pad = (-m_loc) % tp
    # decomposed allreduce = quantized reduce-scatter ring (span recorded by
    # the primitive under ``site``) + tiled all-gather of the small serve-
    # dtype row blocks, recorded here — same shape math as the fp path
    record_collective(site + ".gather", "all_gather",
                      (tp - 1) * ((m_loc + pad) // tp) * n
                      * jnp.dtype(dtype).itemsize, tp, overlapped=False)

    def body(x_l, q_l, s_l):
        bl, tl, kl = x_l.shape
        x2 = x_l.reshape(bl * tl, kl)
        if pad:
            x2 = jnp.pad(x2, ((0, pad), (0, 0)))
        y_loc, _ = fused_quant_matmul_reduce_scatter(
            x2, q_l, s_l, AXIS_TENSOR, bits=bits, wire_bits=cfg.chunk_bits,
            quant_block=cfg.quant_block, bidirectional=cfg.bidirectional,
            interpret=interpret, site=site)
        y = jax.lax.all_gather(y_loc.astype(dtype), AXIS_TENSOR, axis=0,
                               tiled=True)
        if pad:
            y = y[:bl * tl]
        return y.reshape(bl, tl, -1)

    bspec = batch_axes or None
    return shard_map(
        body, mesh=mesh.mesh, axis_names=set(batch_axes) | {AXIS_TENSOR},
        in_specs=(P(bspec, None, AXIS_TENSOR), P(AXIS_TENSOR, None),
                  P(AXIS_TENSOR, None)),
        out_specs=P(bspec, None, None), check_vma=False)(x, q, scales)
