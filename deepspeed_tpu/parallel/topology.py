"""Cartesian process/device topology — pure grid math.

Behavioural equivalent of the reference's ``deepspeed/runtime/pipe/topology.py``
(``ProcessTopology:9``, ``PipeModelDataParallelTopology:243``, ``PipelineParallelGrid:249``).
On TPU the mesh (parallel/mesh.py) is the live object; this class remains useful for checkpoint
reshaping, launcher math, pipeline rank mapping, and tests — anywhere ranks must be mapped to
named coordinates without devices present.
"""

from collections import namedtuple
from itertools import product as _cartesian
from typing import Dict, List


class ProcessTopology:
    """Maps n-dimensional grid coordinates <-> linear ranks.

    Axes are ordered outer-first: the LAST axis varies fastest with rank (row-major), matching
    the reference's behaviour.
    """

    def __init__(self, axes: List[str], dims: List[int]):
        if not (len(axes) == len(dims)):
            raise AssertionError('len(axes) == len(dims)')
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping: Dict = {}
        for coord in _cartesian(*[range(d) for d in self.dims]):
            key = dict(zip(self.axes, coord))
            self.mapping[self.ProcessCoord(**key)] = len(self.mapping)

    def get_rank(self, **coord_kwargs) -> int:
        if len(coord_kwargs) != len(self.axes):
            raise ValueError(f"get_rank() needs all axes {self.axes}")
        return self.mapping[self.ProcessCoord(**coord_kwargs)]

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank: int, omit_axes=("data", "pipe"), inner_sep="_",
                      outer_sep="-") -> str:
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        for ax in axes:
            ax_rank = getattr(self.get_coord(rank=rank), ax)
            names.append(f"{ax}{inner_sep}{ax_rank:02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_coord(self, rank: int):
        for coord, r in self.mapping.items():
            if r == rank:
                return coord
        raise ValueError(f"rank {rank} not found in topology")

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that differ only along ``axis`` (the axis 'communicators')."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for other_coord in _cartesian(*[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, other_coord))
            ranks = [self.get_rank(**{axis: i}, **fixed) for i in range(self.get_dim(axis))]
            lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """Ranks whose coordinates match all given axis=value filters."""
        def _match(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())
        return sorted(r for c, r in self.mapping.items() if _match(c))

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        return sorted(r for c, r in self.mapping.items() if getattr(c, axis) == idx)

    def world_size(self) -> int:
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """Reference ``topology.py:PipeDataParallelTopology`` — hybrid pipeline+data."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """Reference ``topology.py:243`` — 3D pipeline/model/data grid."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Rank-group bookkeeping over a ProcessTopology (reference ``topology.py:249``).

    Mesh-free: answers 'which global ranks form my pipe/data/model group', used by the pipeline
    engine's p2p maps and by checkpoint reshaping.
    """

    def __init__(self, topology: ProcessTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.world_size = topology.world_size()
        self.data_parallel_size = max(1, topology.get_dim("data"))
        self.pipe_parallel_size = max(1, topology.get_dim("pipe"))
        self.model_parallel_size = max(1, topology.get_dim("model"))
        self.slice_parallel_size = self.model_parallel_size
        if not (self.world_size == (self.data_parallel_size * self.pipe_parallel_size *
                                   self.model_parallel_size)):
            raise AssertionError('self.world_size == (self.data_parallel_size * self.pipe_parallel_size * self.model_parallel_size)')
        coord = topology.get_coord(global_rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)
        self.model_parallel_id = getattr(coord, "model", 0) if "model" in topology.axes else 0

    def get_stage_id(self) -> int:
        return self.stage_id

    def get_data_parallel_id(self) -> int:
        return self.data_parallel_id

    def get_pipe_parallel_rank(self) -> int:
        return self.stage_id

    def get_data_parallel_rank(self) -> int:
        return self.data_parallel_id

    def get_model_parallel_rank(self) -> int:
        return self.model_parallel_id

    def get_pipe_parallel_world_size(self) -> int:
        return self.pipe_parallel_size

    def get_data_parallel_world_size(self) -> int:
        return self.data_parallel_size

    def get_model_parallel_world_size(self) -> int:
        return self.model_parallel_size

    def pipe_group(self) -> List[int]:
        filt = {"data": self.data_parallel_id}
        if "model" in self._topo.axes:
            filt["model"] = self.model_parallel_id
        return self._topo.filter_match(**filt)

    def data_group(self) -> List[int]:
        filt = {"pipe": self.stage_id}
        if "model" in self._topo.axes:
            filt["model"] = self.model_parallel_id
        return self._topo.filter_match(**filt)

    def stage_to_global(self, stage_id: int) -> int:
        group = self.pipe_group()
        return group[stage_id]

    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_last_stage(self) -> bool:
        return self.stage_id == self.pipe_parallel_size - 1
