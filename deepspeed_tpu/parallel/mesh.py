"""Device mesh construction and axis bookkeeping.

This is the TPU-native replacement for the reference's process-group machinery
(``deepspeed/utils/groups.py``, ``runtime/pipe/topology.py:PipelineParallelGrid``): instead of
NCCL communicators per parallel dimension, we build one `jax.sharding.Mesh` with named axes and
express every parallel strategy as a PartitionSpec over those axes. XLA then compiles the
collectives onto ICI links.

Axis semantics (SURVEY §2.3 mapping):

- ``pipe``   — pipeline stages (reference ``runtime/pipe/``).
- ``data``   — pure data parallelism (replicated params; grads psum over this axis).
- ``fsdp``   — the ZeRO axis: optimizer state (stage 1), gradients (stage 2) and parameters
               (stage 3) shard over it. With ZeRO enabled and ``fsdp == 1`` the engine folds the
               ``data`` axis into ``fsdp`` so configs need not spell both.
- ``expert`` — MoE expert parallelism (reference ``moe/``): a subdivision of data parallelism;
               non-expert params treat it as extra DP, expert params shard over it.
- ``seq``    — sequence/context parallelism (ring attention) — absent in the reference
               snapshot; first-class here.
- ``tensor`` — megatron-style tensor parallelism, innermost so TP collectives ride the
               fastest ICI links.

Batch sharding: the global batch dim shards over ``(data, fsdp, expert)``; the sequence dim
shards over ``seq``. ``dp_world_size`` (for batch-triple arithmetic) is therefore
``data * fsdp * expert``.
"""

import os
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.logging import logger

AXIS_PIPE = "pipe"
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_EXPERT = "expert"
AXIS_SEQ = "seq"
AXIS_TENSOR = "tensor"

# Outer→inner device-order: pipeline stages furthest apart, TP closest.
MESH_AXES = (AXIS_PIPE, AXIS_DATA, AXIS_FSDP, AXIS_EXPERT, AXIS_SEQ, AXIS_TENSOR)

# Axes over which a (batch, ...) input's leading dim is sharded.
BATCH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_EXPERT)


class MeshSpec:
    """A named mesh plus derived axis bookkeeping.

    Built from a ``MeshConfig`` (config block ``"mesh"``); ``data: -1`` infers the data-axis
    size from the device count divided by the other axes.
    """

    def __init__(self, axis_sizes: Dict[str, int], devices: Optional[Sequence] = None):
        devices = list(devices if devices is not None else jax.devices())
        devices = order_devices_for_dcn(devices)
        n = len(devices)
        sizes = {ax: int(axis_sizes.get(ax, 1)) for ax in MESH_AXES}
        inferred = [ax for ax in MESH_AXES if sizes[ax] in (-1, 0)]
        if len(inferred) > 1:
            raise ValueError(f"At most one mesh axis may be -1 (got {inferred})")
        if inferred:
            fixed = 1
            for ax in MESH_AXES:
                if sizes[ax] > 0:
                    fixed *= sizes[ax]
            if n % fixed != 0:
                raise ValueError(
                    f"Device count {n} not divisible by product of fixed axes {fixed}")
            sizes[inferred[0]] = n // fixed
        total = int(np.prod([sizes[ax] for ax in MESH_AXES]))
        if total != n:
            raise ValueError(
                f"Mesh axis sizes {sizes} produce {total} devices but {n} are available")
        self.axis_sizes = sizes
        shape = tuple(sizes[ax] for ax in MESH_AXES)
        dev_array = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(dev_array, MESH_AXES)
        logger.info(f"MeshSpec: {sizes} over {n} devices")

    @classmethod
    def from_config(cls, mesh_config, devices: Optional[Sequence] = None,
                    zero_stage: int = 0) -> "MeshSpec":
        sizes = {
            AXIS_PIPE: mesh_config.pipe,
            AXIS_DATA: mesh_config.data,
            AXIS_FSDP: mesh_config.fsdp,
            AXIS_EXPERT: mesh_config.expert,
            AXIS_SEQ: mesh_config.seq,
            AXIS_TENSOR: mesh_config.tensor,
        }
        if zero_stage > 0 and sizes[AXIS_FSDP] == 1:
            # ZeRO shards over fsdp; fold the (possibly inferred) data axis into it so that
            # "zero stage 3 on N chips" means N-way param sharding without extra config.
            sizes[AXIS_FSDP] = sizes[AXIS_DATA]
            sizes[AXIS_DATA] = 1
        return cls(sizes, devices)

    # ------------------------------------------------------------------ sizes
    def size(self, axis: str) -> int:
        return self.axis_sizes[axis]

    @property
    def dp_world_size(self) -> int:
        return (self.axis_sizes[AXIS_DATA] * self.axis_sizes[AXIS_FSDP] *
                self.axis_sizes[AXIS_EXPERT])

    @property
    def n_devices(self) -> int:
        return self.mesh.size

    # -------------------------------------------------------------- shardings
    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def batch_spec(self, extra_dims: int = 0, shard_seq_dim: Optional[int] = None) -> P:
        """PartitionSpec for a (batch, seq?, ...) array."""
        dims: List = [BATCH_AXES]
        for i in range(extra_dims):
            dims.append(AXIS_SEQ if (shard_seq_dim is not None and i + 1 == shard_seq_dim)
                        else None)
        return P(*dims)

    def batch_sharding(self, extra_dims: int = 0,
                       shard_seq_dim: Optional[int] = None) -> NamedSharding:
        return self.sharding(self.batch_spec(extra_dims, shard_seq_dim))

    def replicated(self) -> NamedSharding:
        return self.sharding(P())

    # ------------------------------------------------------------ reference-API shims
    # Names mirror deepspeed/utils/groups.py so ported user code reads naturally.
    def get_data_parallel_world_size(self) -> int:
        return self.dp_world_size

    def get_model_parallel_world_size(self) -> int:
        return self.axis_sizes[AXIS_TENSOR]

    def get_expert_parallel_world_size(self) -> int:
        return self.axis_sizes[AXIS_EXPERT]

    def get_pipe_parallel_world_size(self) -> int:
        return self.axis_sizes[AXIS_PIPE]

    def get_sequence_parallel_world_size(self) -> int:
        return self.axis_sizes[AXIS_SEQ]


def order_devices_for_dcn(devices: Sequence) -> List:
    """Order devices so slice boundaries align with OUTER mesh axes.

    Multi-slice TPU topologies connect chips within a slice by ICI and slices by
    DCN (data-center network, ~100x lower bandwidth). ``MESH_AXES`` places ``pipe``
    then ``data`` outermost precisely so that, when the device list enumerates one
    whole slice before the next, the axes that cross slice boundaries are the
    bandwidth-tolerant ones (pipeline p2p, data-parallel gradient reduction) while
    tensor/seq/expert collectives stay on ICI — the standard multi-slice recipe
    (cf. ``jax.experimental.mesh_utils.create_hybrid_device_mesh``).

    Sorts by (slice_index, device id); devices without ``slice_index`` (single
    slice, CPU backends) keep their original order.
    """
    try:
        slice_ids = {getattr(d, "slice_index", None) for d in devices}
    except Exception:
        return list(devices)
    if None in slice_ids or len(slice_ids) <= 1:
        return list(devices)
    return sorted(devices, key=lambda d: (d.slice_index, d.id))


_GLOBAL_MESH: Optional[MeshSpec] = None


def set_global_mesh(spec: MeshSpec):
    global _GLOBAL_MESH
    _GLOBAL_MESH = spec


def get_global_mesh() -> Optional[MeshSpec]:
    return _GLOBAL_MESH


def default_mesh(devices: Optional[Sequence] = None) -> MeshSpec:
    """All devices on the data axis (plain DP)."""
    return MeshSpec({AXIS_DATA: -1}, devices)
