from .mesh import (
    MeshSpec,
    default_mesh,
    get_global_mesh,
    set_global_mesh,
    AXIS_PIPE,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_SEQ,
    AXIS_TENSOR,
    MESH_AXES,
    BATCH_AXES,
)
from .topology import (
    ProcessTopology,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    PipelineParallelGrid,
)
