from .mesh import (
    MeshSpec,
    default_mesh,
    get_global_mesh,
    set_global_mesh,
    AXIS_PIPE,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_SEQ,
    AXIS_TENSOR,
    MESH_AXES,
    BATCH_AXES,
)
from .overlap import (
    OverlapConfig,
    resolve_overlap_config,
    set_overlap_config,
    get_overlap_config,
    overlap_scope,
    chunked_allgather_matmul,
    chunked_matmul_reduce_scatter,
    allgather_matmul_monolithic,
    matmul_reduce_scatter_monolithic,
    row_parallel_dense_apply,
    RowParallelDense,
)
from .qring import (
    fused_quant_allgather_matmul,
    fused_quant_matmul_reduce_scatter,
    quant_row_parallel_apply,
)
from .topology import (
    ProcessTopology,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    PipelineParallelGrid,
)
