"""Decomposed collectives for comm-compute overlap on the TP/MoE hot paths.

Monolithic ``lax.all_gather`` / ``lax.psum`` around a tensor-parallel matmul
serialize ICI traffic behind the MXU: the chip computes, then it communicates.
The T3 line of work (arxiv 2401.16677) and XLA's own ``collective_matmul`` pass
show the same matmul decomposed into ``tp`` ring steps hides most of the
collective: while chunk ``i`` transfers over ICI, chunk ``i-1`` multiplies.
This module provides those decomposed primitives plus the config plumbing that
turns them on behind the ``"comm_overlap"`` config block:

- :func:`chunked_allgather_matmul` — ``all_gather(x) @ w`` as a ``ppermute``
  ring; each output row-block is produced by exactly one matmul over unchanged
  operands, so it is **bit-identical** to the monolithic form.
- :func:`chunked_matmul_reduce_scatter` — ``psum_scatter(x @ w)`` as a ring of
  (block matmul + accumulate) steps; the cross-shard summation order is the
  ring visit order, so results match the monolithic form up to fp summation
  order (exact in integer/exact-representable cases; last-ulp in bf16/fp32).
- bidirectional variants of both (chunks travel both ICI directions at once —
  half the serial latency, both links busy).
- :func:`row_parallel_dense_apply` / :class:`RowParallelDense` — GSPMD-callable
  row-parallel dense (the ``o_proj``/``fc_out`` allreduce sites) that lowers to
  matmul-reduce-scatter + all-gather inside a ``shard_map`` when overlap is
  enabled, with an exact-numerics monolithic fallback otherwise.
- :func:`chunked_expert_exchange` — the MoE dispatch/combine a2a split into
  capacity chunks so each chunk's ICI exchange overlaps the previous chunk's
  expert FFN (bitwise-exact: the FFN is per-token and the combine einsum stays
  whole).

The quantized-collective half of the config block (``quantized_allreduce``,
EQuARX-style intN blockwise psum for DP gradient sync, arxiv 2506.17615) lives
in ``comm/compressed.py`` next to the 1-bit machinery it composes with; the
engine consumes it directly. The composition of BOTH halves — the ppermute
ring with a quantized wire payload and a dequant-GEMM per ring step (serving
TP decode over quantized weights) — lives in ``parallel/qring.py``.

Every decomposed/monolithic call site records a trace-time bytes-on-wire span
(``utils.comms_logging.collective_spans``) so MonitorMaster and ``bench.py
--overlap`` can report collective volume and overlap ratio.
"""

import contextlib
import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..utils.comms_logging import record_collective
from ..utils.jax_compat import shard_map
from ..utils.nvtx import named_scope
from .mesh import AXIS_PIPE, AXIS_SEQ, AXIS_TENSOR, BATCH_AXES, get_global_mesh


def _scoped(name: str):
    """Trace the decorated collective under a ``jax.named_scope``: the name
    lands in XLA op metadata, so an on-demand profiler capture shows the ring
    steps / fallbacks as labeled device ops aligned with the host spans."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with named_scope(name):
                return fn(*args, **kwargs)
        return wrapped
    return deco


# --------------------------------------------------------------------- config
@dataclasses.dataclass(frozen=True)
class OverlapConfig:
    """Parsed ``"comm_overlap"`` config block.

    - ``enabled``: master switch; everything below is inert without it.
    - ``collective_matmul``: decomposed (chunked, ppermute-ring) TP matmuls +
      chunked MoE dispatch/combine.
    - ``quantized_allreduce``: intN blockwise-scaled DP gradient sync with
      error feedback (plain-DP regime only; see ``runtime/engine.py``).
    - ``chunk_bits``: wire width of the quantized collectives — the intN
      payload of the fused quantized ring (``parallel/qring.py``) and the DP
      gradient sync. One of {4, 8, 16} (int4 nibble-packed / EQuARX int8 /
      int16); anything else is a loud error, never a silent clamp.
    - ``bidirectional``: ring chunks travel both ICI directions.
    - ``quant_block``: elements per absmax scale block of the quantized
      collectives (even, >= 8 — int4 packs two wire elements per byte).
    - ``moe_chunks``: target chunk count for the MoE a2a pipeline.
    """
    enabled: bool = False
    collective_matmul: bool = True
    quantized_allreduce: bool = False
    chunk_bits: int = 8
    bidirectional: bool = True
    quant_block: int = 256
    moe_chunks: int = 4

    def __post_init__(self):
        from ..comm.compressed import WIRE_BITS
        if self.chunk_bits not in WIRE_BITS:
            raise ValueError(
                f"comm_overlap.chunk_bits={self.chunk_bits} unsupported — the "
                f"quantized wire is blockwise-scaled intN with N in "
                f"{sorted(WIRE_BITS)} (int4 nibble-packed / EQuARX int8 / "
                "int16); widths are validated, not clamped")
        if self.quant_block < 8 or self.quant_block % 2:
            raise ValueError(
                f"comm_overlap.quant_block={self.quant_block} invalid "
                "(even, >= 8)")

    @property
    def matmul_active(self) -> bool:
        return self.enabled and self.collective_matmul


def resolve_overlap_config(raw) -> OverlapConfig:
    """Accepts None | dict | pydantic model | OverlapConfig."""
    if raw is None:
        return OverlapConfig()
    if isinstance(raw, OverlapConfig):
        return raw
    if hasattr(raw, "model_dump"):
        raw = raw.model_dump()
    elif hasattr(raw, "dict") and not isinstance(raw, dict):
        raw = raw.dict()
    fields = {f.name for f in dataclasses.fields(OverlapConfig)}
    unknown = set(raw) - fields
    if unknown:
        raise ValueError(f"unknown comm_overlap keys: {sorted(unknown)} "
                         f"(known: {sorted(fields)})")
    return OverlapConfig(**raw)


_OVERLAP_CONFIG: OverlapConfig = OverlapConfig()


def set_overlap_config(cfg: Optional[OverlapConfig]):
    global _OVERLAP_CONFIG
    _OVERLAP_CONFIG = cfg if cfg is not None else OverlapConfig()


def get_overlap_config() -> OverlapConfig:
    return _OVERLAP_CONFIG


@contextlib.contextmanager
def overlap_scope(cfg: Optional[OverlapConfig]):
    """Install ``cfg`` for the duration of a trace. Used by the compiled-step
    builders (``inference/decode_fns.py``) so each engine's compiled bodies
    trace with THAT engine's overlap setting regardless of ambient global
    state (engines with different settings coexist in one process)."""
    if cfg is None:
        yield
        return
    prev = get_overlap_config()
    set_overlap_config(cfg)
    try:
        yield
    finally:
        set_overlap_config(prev)


# ------------------------------------------------------- ring primitives
# All primitives below run INSIDE a shard_map whose manual axes include
# ``axis_name``. Chunk count == axis size: one ring step per shard, the
# granularity at which XLA's latency-hiding scheduler can slide each ppermute
# under the neighbouring chunk's matmul.

def _ring_perm(W: int, step: int = 1):
    return [(p, (p + step) % W) for p in range(W)]


def _record_ring(site, op, per_shard_bytes, axis_name, overlapped):
    """Trace-time span for a ring primitive; ``site=None`` skips (the caller
    — e.g. ``row_parallel_dense_apply`` — is recording at its own level)."""
    if site is not None:
        W = jax.lax.psum(1, axis_name)
        record_collective(site, op, (W - 1) * per_shard_bytes, W,
                          overlapped=overlapped)


@_scoped("comm.allgather_matmul_monolithic")
def allgather_matmul_monolithic(x, w, axis_name, *, site=None):
    """Exact-numerics fallback: ``all_gather(x, tiled) @ w``."""
    _record_ring(site, "all_gather", x.size * x.dtype.itemsize, axis_name,
                 overlapped=False)
    g = jax.lax.all_gather(x, axis_name, axis=0, tiled=True)
    return g @ w


@_scoped("comm.matmul_reduce_scatter_monolithic")
def matmul_reduce_scatter_monolithic(x, w, axis_name, *, site=None):
    """Exact-numerics fallback: ``psum_scatter(x @ w, scatter dim 0, tiled)``."""
    W = jax.lax.psum(1, axis_name)
    _record_ring(site, "reduce_scatter",
                 x.shape[0] // W * w.shape[1] * jnp.result_type(x, w).itemsize,
                 axis_name, overlapped=False)
    return jax.lax.psum_scatter(x @ w, axis_name, scatter_dimension=0,
                                tiled=True)


@_scoped("comm.chunked_allgather_matmul")
def chunked_allgather_matmul(x, w, axis_name, *, bidirectional: bool = True,
                             site=None):
    """``all_gather(x, axis=0, tiled) @ w`` as a ppermute ring.

    ``x``: this shard's ``(m_loc, k)`` row block (sharded over ``axis_name``);
    ``w``: ``(k, n)`` local operand. Returns ``(W*m_loc, n)``.

    Step ``s`` multiplies the row block that arrived on the ring while step
    ``s-1``'s block was multiplying — the transfer hides under the MXU. Each
    output row block is one matmul over unchanged operands: bit-identical to
    the monolithic form.
    """
    W = jax.lax.psum(1, axis_name)
    if W == 1:
        return x @ w
    _record_ring(site, "all_gather", x.size * x.dtype.itemsize, axis_name,
                 overlapped=True)
    idx = jax.lax.axis_index(axis_name)
    m_loc, n = x.shape[0], w.shape[1]
    out = jnp.zeros((W * m_loc, n), dtype=jnp.result_type(x.dtype, w.dtype))

    def write(out, block, src):
        y = block @ w
        return jax.lax.dynamic_update_slice(out, y, (src * m_loc, 0))

    if not bidirectional:
        cur = x
        for s in range(W):
            out = write(out, cur, (idx - s) % W)
            if s != W - 1:
                cur = jax.lax.ppermute(cur, axis_name, _ring_perm(W, 1))
        return out

    fwd = bwd = x
    out = write(out, x, idx)
    for s in range(1, W // 2 + 1):
        fwd = jax.lax.ppermute(fwd, axis_name, _ring_perm(W, 1))
        out = write(out, fwd, (idx - s) % W)
        if s <= (W - 1) // 2:
            bwd = jax.lax.ppermute(bwd, axis_name, _ring_perm(W, -1))
            out = write(out, bwd, (idx + s) % W)
    return out


@_scoped("comm.chunked_matmul_reduce_scatter")
def chunked_matmul_reduce_scatter(x, w, axis_name, *,
                                  bidirectional: bool = True, site=None):
    """``psum_scatter(x @ w, scatter dim 0, tiled)`` as a compute/accumulate ring.

    ``x``: ``(m, k)`` local operand (each shard holds its partial-sum
    contribution, e.g. the row-parallel activation slice); ``w``: ``(k, n)``.
    ``m`` must divide by the axis size. Returns ``(m/W, n)``: shard ``p`` ends
    holding row block ``p`` fully summed.

    Row block ``b``'s accumulator starts at shard ``b+1`` and travels the ring;
    at each step the shard adds its own partial for the block just as the next
    hop's transfer begins — the ICI hop hides under the block matmul. The
    cross-shard sum runs in ring-visit order (fp summation order differs from
    the monolithic psum by at most last-ulp; exact for exactly-representable
    sums).
    """
    W = jax.lax.psum(1, axis_name)
    if W == 1:
        return x @ w
    idx = jax.lax.axis_index(axis_name)
    m, k = x.shape
    if m % W != 0:
        # must survive python -O: dynamic_slice CLAMPS out-of-range block
        # starts, so an unguarded ragged m would silently double-sum rows
        raise ValueError(
            f"chunked_matmul_reduce_scatter: m={m} not divisible by "
            f"axis size {W} — pad rows first (see row_parallel_dense_apply)")
    m_blk = m // W
    _record_ring(site, "reduce_scatter",
                 m_blk * w.shape[1] * jnp.result_type(x, w).itemsize,
                 axis_name, overlapped=True)

    def partial(b, ww):
        rows = jax.lax.dynamic_slice(x, (b * m_blk, 0), (m_blk, k))
        return rows @ ww

    if not bidirectional or w.shape[1] % 2:
        acc = partial((idx - 1) % W, w)
        for s in range(1, W):
            acc = jax.lax.ppermute(acc, axis_name, _ring_perm(W, 1))
            acc = acc + partial((idx - 1 - s) % W, w)
        return acc

    # bidirectional: column halves travel opposite ring directions, using both
    # ICI links each step at half the per-step payload
    h = w.shape[1] // 2
    wa, wb = w[:, :h], w[:, h:]
    acc_a = partial((idx - 1) % W, wa)
    acc_b = partial((idx + 1) % W, wb)
    for s in range(1, W):
        acc_a = jax.lax.ppermute(acc_a, axis_name, _ring_perm(W, 1))
        acc_a = acc_a + partial((idx - 1 - s) % W, wa)
        acc_b = jax.lax.ppermute(acc_b, axis_name, _ring_perm(W, -1))
        acc_b = acc_b + partial((idx + 1 + s) % W, wb)
    return jnp.concatenate([acc_a, acc_b], axis=1)


# --------------------------------------------- GSPMD-callable row-parallel dense
def _overlap_dense_eligible(mesh, b, t, k, cfg: OverlapConfig):
    if mesh is None or not cfg.matmul_active:
        return False, (), 1
    tp = mesh.size(AXIS_TENSOR)
    if tp <= 1 or k % tp or mesh.size(AXIS_SEQ) > 1 or mesh.size(AXIS_PIPE) > 1:
        return False, (), 1
    batch_axes = tuple(ax for ax in BATCH_AXES if mesh.size(ax) > 1)
    bsz = int(np.prod([mesh.size(ax) for ax in batch_axes])) if batch_axes else 1
    if batch_axes and b % bsz:
        return False, (), 1
    # chunking needs at least one row per ring step after batch sharding
    if (b // max(bsz, 1)) * t < tp:
        return False, (), 1
    return True, batch_axes, tp


def row_parallel_dense_apply(x, kernel, bias, dtype, *, site: str = "tp.row_dense"):
    """Row-parallel dense ``y = x @ kernel + bias`` with comm-compute overlap.

    ``x``: ``(b, t, k)`` activations; ``kernel``: ``(k, n)`` sharded
    ``P(tensor, None)`` by the model's param specs; ``bias``: ``(n,)`` or None.

    When the overlap config is active and shapes divide, lowers to a
    ``shard_map`` over {batch axes} ∪ {tensor}: local rows × local kernel slice
    through :func:`chunked_matmul_reduce_scatter`, then a tiled all-gather of
    the (small, d_model-wide) row blocks — replacing the monolithic allreduce
    GSPMD would insert, with the heavy matmul overlapping the scatter ring.
    Falls back to the plain (GSPMD-collective) matmul otherwise — numerics of
    the two paths agree (summation-order-exact for the gather, last-ulp for
    the scatter; pinned by ``tests/unit/parallel/test_overlap.py``).
    """
    cfg = get_overlap_config()
    mesh = get_global_mesh()
    b, t, k = x.shape
    n = kernel.shape[-1]
    x = x.astype(dtype)
    kernel = kernel.astype(dtype)
    ok, batch_axes, tp = _overlap_dense_eligible(mesh, b, t, k, cfg)
    if not ok:
        if mesh is not None and mesh.size(AXIS_TENSOR) > 1:
            record_collective(site + ".monolithic", "all_reduce",
                              b * t * n * jnp.dtype(dtype).itemsize,
                              mesh.size(AXIS_TENSOR), overlapped=False)
        y = x @ kernel
        return y if bias is None else y + bias.astype(dtype)

    bsz = int(np.prod([mesh.size(ax) for ax in batch_axes])) if batch_axes else 1
    m_loc = (b // bsz) * t
    pad = (-m_loc) % tp
    # decomposed allreduce = reduce-scatter (overlapped under the matmul;
    # span recorded by the primitive under ``site``) + tiled all-gather of the
    # small row blocks, recorded here: (W-1) blocks of (m/W)·n on the wire
    record_collective(site + ".gather", "all_gather",
                      (tp - 1) * ((m_loc + pad) // tp) * n
                      * jnp.dtype(dtype).itemsize,
                      tp, overlapped=False)
    # NOTE on autodiff: the kernel's in_spec leaves the batch axes unmentioned
    # (replicated); shard_map's transpose psums its cotangent over those axes
    # itself, so no explicit conjugate op is needed here (adding one would
    # double-count — pinned by the TP×DP grad parity test).
    def body(x_l, w_l):
        bl, tl, kl = x_l.shape
        x2 = x_l.reshape(bl * tl, kl)
        if pad:
            x2 = jnp.pad(x2, ((0, pad), (0, 0)))
        y_loc = chunked_matmul_reduce_scatter(
            x2, w_l, AXIS_TENSOR, bidirectional=cfg.bidirectional, site=site)
        y = jax.lax.all_gather(y_loc, AXIS_TENSOR, axis=0, tiled=True)
        if pad:
            y = y[:bl * tl]
        return y.reshape(bl, tl, -1)

    xspec = P(batch_axes or None, None, AXIS_TENSOR)
    manual = set(batch_axes) | {AXIS_TENSOR}
    y = shard_map(body, mesh=mesh.mesh, axis_names=manual,
                  in_specs=(xspec, P(AXIS_TENSOR, None)),
                  out_specs=P(batch_axes or None, None, None),
                  check_vma=False)(x, kernel)
    return y if bias is None else y + bias.astype(dtype)


# flax module mirroring nn.Dense's parameter tree (kernel/bias names, fp32
# params, compute-dtype cast) so swapping it into a model changes NOTHING about
# checkpoints — only how the row-parallel matmul lowers.
import flax.linen as nn  # noqa: E402  (after jax; mirrors models/* import order)


def raw_or_param(mdl: nn.Module, name: str, init_fn, shape):
    """Declare a weight at init; RAW-fetch it at apply.

    The serving engine replaces quantizable kernels with quant nodes
    (``{__int8_q__|__int4_q__, *_scale__}`` — ``ops/quantizer``). Those must
    flow through flax untouched: ``self.param`` re-runs the initializer under
    ``eval_shape`` and zips leaf shapes, which a packed int4 payload
    (``(k//2, n)``) fails. Raw scope access skips that validation; the fp
    (training/unquantized) tree is bit-identical either way. Shared by every
    quantizable projection module (:class:`RowParallelDense` here,
    ``QuantDense``/``_ExpertWeights`` in ``models/causal_lm.py``)."""
    if mdl.is_initializing() or not mdl.has_variable("params", name):
        return mdl.param(name, init_fn, shape, jnp.float32)
    return mdl.scope.get_variable("params", name)


class RowParallelDense(nn.Module):
    """Drop-in for ``nn.Dense`` at row-parallel TP sites (o_proj / fc_out).

    At serve time the engine may replace ``kernel`` with a quant node
    (``ops/quantizer``): when ``comm_overlap`` is active the projection then
    runs the fused quantized ring (``parallel/qring.py``) — a dequant-GEMM
    per ring step over the shard's whole packed slab (group boundaries never
    cross the wire, only fp accumulator chunks do), with the ring payload
    itself quantized to ``chunk_bits``. Ineligible shapes (or overlap off)
    keep the PR-5 fused kernel + monolithic psum."""
    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros
    span: str = "tp.row_dense"

    @nn.compact
    def __call__(self, x):
        kernel = raw_or_param(self, "kernel", self.kernel_init,
                              (x.shape[-1], self.features))
        bias = (self.param("bias", self.bias_init, (self.features,), jnp.float32)
                if self.use_bias else None)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None]
        from ..ops.quantizer import is_quant_node, quant_dense_apply
        if is_quant_node(kernel):
            y = quant_dense_apply(x, kernel, bias, self.dtype, parallel="row",
                                  site=self.span)
        else:
            y = row_parallel_dense_apply(x, kernel, bias, self.dtype,
                                         site=self.span)
        return y[:, 0] if squeeze else y


# ----------------------------------------------------------- MoE a2a pipeline
def moe_overlap_chunks(cfg: OverlapConfig, expert_parallel: int, cap: int) -> int:
    """Chunk count for the MoE dispatch/combine exchange: the largest divisor
    of ``cap`` not exceeding ``cfg.moe_chunks`` (1 = no chunking)."""
    if not cfg.matmul_active or expert_parallel <= 1 or cap <= 1:
        return 1
    target = max(1, min(cfg.moe_chunks, cap))
    for c in range(target, 1, -1):
        if cap % c == 0:
            return c
    return 1


@_scoped("comm.chunked_expert_exchange")
def chunked_expert_exchange(expert_in, expert_fn, sharding, n_chunks: int,
                            *, site: str = "moe.a2a"):
    """Run the expert exchange + FFN in ``n_chunks`` capacity slices.

    ``expert_in``: ``(e, c, m)`` token-major dispatch tensor; ``sharding``:
    the ``P(expert, ...)`` NamedSharding constraint that lowers to the
    all-to-all; ``expert_fn``: per-token expert FFN. Chunk ``i+1``'s layout
    exchange overlaps chunk ``i``'s FFN under XLA's async collectives. The FFN
    is per-token and slices are disjoint, so the concatenated result is
    bitwise-identical to the unchunked exchange.
    """
    e, c, m = expert_in.shape
    n_ranks = None
    mesh = get_global_mesh()
    if mesh is not None:
        from .mesh import AXIS_EXPERT
        n_ranks = mesh.size(AXIS_EXPERT)
    # full payload regardless of chunking: n_chunks exchanges move the same
    # total bytes as the monolithic exchange — recording one chunk's slice
    # would understate the overlap config's traffic by n_chunks in the A/B
    record_collective(site, "all_to_all",
                      expert_in.size * expert_in.dtype.itemsize,
                      n_ranks or 1, overlapped=n_chunks > 1)
    if n_chunks <= 1:
        expert_in = jax.lax.with_sharding_constraint(expert_in, sharding)
        out = expert_fn(expert_in)
        return jax.lax.with_sharding_constraint(out, sharding)
    cs = c // n_chunks
    outs = []
    for i in range(n_chunks):
        sl = jax.lax.with_sharding_constraint(
            expert_in[:, i * cs:(i + 1) * cs, :], sharding)
        yo = expert_fn(sl)
        outs.append(jax.lax.with_sharding_constraint(yo, sharding))
    return jnp.concatenate(outs, axis=1)
