"""Communication facade.

TPU-native analogue of reference ``deepspeed/comm/comm.py`` (free functions :220-596,
``init_distributed:590``, ``timed_op:108``) and ``comm/torch.py:TorchBackend``.

On TPU, *in-graph* collectives are sharding-induced and XLA-scheduled — there is no NCCL-style
process-group API to wrap. What remains genuinely process-level (and therefore lives here):

- ``init_distributed`` → ``jax.distributed.initialize`` (multi-host rendezvous, the analogue of
  ``torch.distributed.init_process_group``); auto-detects single-process runs.
- rank/world queries (process level).
- eager cross-process collectives on host data (checkpoint resharding, tag validation,
  elastic coordination): built on ``jax.experimental.multihost_utils`` / a temporary mesh.
- ``timed_op``-style profiling into :class:`CommsLogger` for the eager ops.

In-graph code uses ``jax.lax.psum/all_gather/ppermute/all_to_all`` over named mesh axes directly
(re-exported here for discoverability).
"""

import functools
import os
import time
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.comms_logging import CommsLogger
from ..utils.logging import logger

# Re-exports: the in-graph collective vocabulary (use inside shard_map/jit over mesh axes).
# all_gather / all_to_all are prefixed lax_ because the eager host-side functions below own
# the reference's names.
from jax.lax import (  # noqa: F401
    psum, pmean, pmax, pmin, ppermute, axis_index, psum_scatter,
)
from jax.lax import all_gather as lax_all_gather  # noqa: F401
from jax.lax import all_to_all as lax_all_to_all  # noqa: F401

comms_logger = CommsLogger()

_INITIALIZED = False


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(dist_backend: Optional[str] = None,
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method: Optional[str] = None,
                     dist_init_required: Optional[bool] = None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Initialise multi-host JAX if the environment calls for it.

    Reference: ``comm/comm.py:init_distributed:590`` (+ ``mpi_discovery:659``). The signature is
    kept for source compatibility; ``dist_backend`` is ignored (XLA owns the transport).
    Single-process (or already-initialised) invocations are no-ops, like the reference.
    """
    global _INITIALIZED
    if config is not None:
        comms_logger.configure(config)
    if _INITIALIZED:
        return

    coord = os.environ.get("COORDINATOR_ADDRESS")
    n_proc = int(os.environ.get("NPROC", os.environ.get("WORLD_SIZE", "1")))
    pid = int(os.environ.get("PROCESS_ID", os.environ.get("RANK", "0")))
    if world_size > 0:
        n_proc = world_size
    if rank >= 0:
        pid = rank
    if coord is None and auto_mpi_discovery and "OMPI_COMM_WORLD_SIZE" in os.environ:
        # MPI launch without explicit env: reference comm.py:mpi_discovery equivalent.
        n_proc = int(os.environ["OMPI_COMM_WORLD_SIZE"])
        pid = int(os.environ["OMPI_COMM_WORLD_RANK"])
    if coord is None and n_proc > 1:
        # torchrun-style env (MASTER_ADDR) or explicit init_method — derive the coordinator
        # rather than silently running n_proc independent single-process worlds.
        if init_method and init_method.startswith("tcp://"):
            coord = init_method[len("tcp://"):]
        elif "MASTER_ADDR" in os.environ:
            port = os.environ.get("MASTER_PORT", str(distributed_port))
            coord = f"{os.environ['MASTER_ADDR']}:{port}"
        else:
            raise RuntimeError(
                f"init_distributed: world_size={n_proc} requested but no coordinator "
                "address found (set COORDINATOR_ADDRESS or MASTER_ADDR, or pass "
                "init_method='tcp://host:port')")
    if coord is not None and n_proc > 1:
        if verbose:
            logger.info(f"Initializing jax.distributed: coordinator={coord} "
                        f"process={pid}/{n_proc}")
        jax.distributed.initialize(coordinator_address=coord, num_processes=n_proc,
                                   process_id=pid)
    elif jax.process_count() > 1 and verbose:
        logger.info("jax.distributed already initialised by the runtime")
    _INITIALIZED = True


def destroy_process_group():
    global _INITIALIZED
    try:
        jax.distributed.shutdown()
    except Exception:
        pass
    _INITIALIZED = False


# ----------------------------------------------------------------- rank queries
def get_rank() -> int:
    """Process index (host rank). Reference ``comm.py:get_rank``."""
    return jax.process_index()


def get_world_size() -> int:
    """Process count. Reference ``comm.py:get_world_size``."""
    return jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", 0))


def get_device_count() -> int:
    return jax.device_count()


def get_local_device_count() -> int:
    return jax.local_device_count()


# ------------------------------------------------------- eager host collectives
def _timed(op_name: str):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not comms_logger.should_profile(op_name):
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            jax.block_until_ready(out) if out is not None else None
            dt = time.perf_counter() - t0
            size = 0
            if args and hasattr(args[0], "nbytes"):
                size = int(args[0].nbytes)
            comms_logger.append(op_name, op_name, dt, size, get_world_size())
            return out
        return wrapper
    return deco


@_timed("all_reduce")
def all_reduce(host_array, op: str = "sum"):
    """Eager cross-process allreduce of a host array (outside jit).

    For in-graph reduction use ``lax.psum`` over mesh axes; this exists for checkpoint-time and
    coordination-time sums, the role the eager path of reference ``comm.py:all_reduce`` plays.
    """
    x = np.asarray(host_array)
    if get_world_size() == 1:
        return x
    from jax.experimental import multihost_utils
    if op == "sum":
        return np.asarray(multihost_utils.process_allgather(x)).sum(axis=0)
    elif op == "max":
        return np.asarray(multihost_utils.process_allgather(x)).max(axis=0)
    elif op == "min":
        return np.asarray(multihost_utils.process_allgather(x)).min(axis=0)
    raise ValueError(f"Unsupported op {op}")


@_timed("all_gather")
def all_gather(host_array):
    """Eager cross-process allgather (stacks along new leading dim)."""
    x = np.asarray(host_array)
    if get_world_size() == 1:
        return x[None]
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x))


@_timed("broadcast")
def broadcast(host_array, src: int = 0):
    x = np.asarray(host_array)
    if get_world_size() == 1:
        return x
    from jax.experimental import multihost_utils
    if src != 0:
        # broadcast_one_to_all sources from process 0; rotate the payload there first
        x = np.asarray(multihost_utils.process_allgather(x))[src]
        return x
    return np.asarray(multihost_utils.broadcast_one_to_all(x))


@_timed("barrier")
def barrier(tag: str = "ds_barrier"):
    """Cross-process sync point. Reference ``comm.py:barrier``."""
    if get_world_size() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(tag)


def broadcast_object_list(obj_list: List[Any], src: int = 0) -> List[Any]:
    """Pickle-transport broadcast, analogue of reference ``comm.py:broadcast_object_list``."""
    if get_world_size() == 1:
        return obj_list
    import pickle
    from jax.experimental import multihost_utils
    payload = np.frombuffer(pickle.dumps(obj_list), dtype=np.uint8)
    # length-prefix exchange so every process allocates identically
    n = int(all_reduce(np.array([payload.size if get_rank() == src else 0]), op="max")[0])
    buf = np.zeros(n, dtype=np.uint8)
    if get_rank() == src:
        buf[:payload.size] = payload
    out = broadcast(buf, src=src)
    return pickle.loads(out.tobytes())


def log_summary():
    """Reference ``comm.py:log_summary:474``."""
    comms_logger.log_all()


def configure(deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None):
    """Reference ``comm.py:configure``."""
    if deepspeed_config is not None:
        comms_logger.configure(deepspeed_config.comms_logger)
    if enabled is not None:
        comms_logger.enabled = enabled
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops
    if verbose is not None:
        comms_logger.verbose = verbose
