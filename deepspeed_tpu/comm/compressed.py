"""Sign-compressed (1-bit) allreduce with error feedback.

Behavioural equivalent of reference ``deepspeed/runtime/comm/nccl.py``
(``NcclBackend.compressed_allreduce:52``) / ``comm/mpi.py``: each worker ships only the
SIGN of its (error-compensated) tensor plus one L1 scale, cutting collective volume
~32× for the momentum exchange of the 1-bit optimizers.

TPU-native realisation: an in-graph collective for use inside ``shard_map`` over a mesh
axis. Signs are bit-packed into uint8 lanes (8 signs/byte) so the ``all_gather`` actually
moves 1 bit per element over ICI; unpack + scale-weighted mean reconstructs the
compressed average. Error feedback (worker residual carried to the next step) preserves
convergence (1-bit Adam paper, Tang et al. 2021).
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(n,) bool -> (ceil(n/8),) uint8 bitmask."""
    n = bits.shape[0]
    pad = (-n) % 8
    b = jnp.pad(bits.astype(jnp.uint8), (0, pad)).reshape(-1, 8)
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(b * weights, axis=1).astype(jnp.uint8)


def _unpack_bits(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """(m,) uint8 -> (n,) bool."""
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & 1
    return bits.reshape(-1)[:n].astype(bool)


def sign_compress(x: jnp.ndarray,
                  error: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Error-compensated 1-bit compression (unpacked): returns
    ``(compressed, new_error)`` with ``compressed + new_error == x + error`` exactly.
    Shared by the 1-bit optimizers (momentum compression) and the wire collective."""
    c = x + error
    scale = jnp.mean(jnp.abs(c))
    compressed = jnp.where(c >= 0, scale, -scale)
    return compressed, c - compressed


def compress_signs(x: jnp.ndarray,
                   error: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray,
                                                jnp.ndarray]:
    """Error-compensated 1-bit compression of a flat fp32 tensor, bit-packed for the
    wire. Returns ``(packed_signs uint8, scale, new_error)`` with
    ``decompress(packed, scale) + new_error == x + error`` exactly.
    """
    c = x + error
    scale = jnp.mean(jnp.abs(c))
    signs = c >= 0
    new_error = c - jnp.where(signs, scale, -scale)
    return _pack_bits(signs), scale, new_error


def compressed_allreduce(x: jnp.ndarray, error: jnp.ndarray,
                         axis_name: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-bit mean over ``axis_name`` (call inside ``shard_map``); returns
    ``(mean of compressed worker tensors, new local error)``.

    Collective volume: n/8 bytes of signs + 4 bytes of scale per worker (vs 4n bytes
    for a full fp32 allreduce).
    """
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    err = error.reshape(-1).astype(jnp.float32)
    packed, scale, new_error = compress_signs(flat, err)
    gathered = jax.lax.all_gather(packed, axis_name)      # (W, n/8) uint8
    scales = jax.lax.all_gather(scale, axis_name)         # (W,)
    n = flat.shape[0]
    signs = jax.vmap(lambda p: _unpack_bits(p, n))(gathered)  # (W, n) bool
    avg = jnp.mean(jnp.where(signs, scales[:, None], -scales[:, None]), axis=0)
    return avg.reshape(shape), new_error.reshape(shape)
