"""Sign-compressed (1-bit) allreduce with error feedback.

Behavioural equivalent of reference ``deepspeed/runtime/comm/nccl.py``
(``NcclBackend.compressed_allreduce:52``) / ``comm/mpi.py``: each worker ships only the
SIGN of its (error-compensated) tensor plus one L1 scale, cutting collective volume
~32× for the momentum exchange of the 1-bit optimizers.

TPU-native realisation: an in-graph collective for use inside ``shard_map`` over a mesh
axis. Signs are bit-packed into uint8 lanes (8 signs/byte) so the ``all_gather`` actually
moves 1 bit per element over ICI; unpack + scale-weighted mean reconstructs the
compressed average. Error feedback (worker residual carried to the next step) preserves
convergence (1-bit Adam paper, Tang et al. 2021).
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from ..utils.nvtx import named_scope


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(n,) bool -> (ceil(n/8),) uint8 bitmask."""
    n = bits.shape[0]
    pad = (-n) % 8
    b = jnp.pad(bits.astype(jnp.uint8), (0, pad)).reshape(-1, 8)
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(b * weights, axis=1).astype(jnp.uint8)


def _unpack_bits(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """(m,) uint8 -> (n,) bool."""
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & 1
    return bits.reshape(-1)[:n].astype(bool)


def sign_compress(x: jnp.ndarray,
                  error: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Error-compensated 1-bit compression (unpacked): returns
    ``(compressed, new_error)`` with ``compressed + new_error == x + error`` exactly.
    Shared by the 1-bit optimizers (momentum compression) and the wire collective."""
    c = x + error
    scale = jnp.mean(jnp.abs(c))
    compressed = jnp.where(c >= 0, scale, -scale)
    return compressed, c - compressed


def compress_signs(x: jnp.ndarray,
                   error: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray,
                                                jnp.ndarray]:
    """Error-compensated 1-bit compression of a flat fp32 tensor, bit-packed for the
    wire. Returns ``(packed_signs uint8, scale, new_error)`` with
    ``decompress(packed, scale) + new_error == x + error`` exactly.
    """
    c = x + error
    scale = jnp.mean(jnp.abs(c))
    signs = c >= 0
    new_error = c - jnp.where(signs, scale, -scale)
    return _pack_bits(signs), scale, new_error


# ------------------------------------------------ intN blockwise (EQuARX)
# The multi-bit siblings of the sign collective above (EQuARX, arxiv
# 2506.17615): per-block absmax scales instead of one global L1 scale, an
# int4/int8/int16 payload instead of packed signs — 7.8x/3.9x/2x wire
# reduction at graded fidelity, with the SAME error-feedback contract as
# sign_compress so the widths compose with (rather than replace) each other:
# transmitted + new_error == x + error. bits=8 is the original EQuARX wire
# used by the DP gradient sync; the fused quantized ring
# (``parallel/qring.py``) selects the width via ``comm_overlap.chunk_bits``.

#: Supported quantized-wire widths (``comm_overlap.chunk_bits``).
WIRE_BITS = (4, 8, 16)

_WIRE_QMAX = {4: 7.0, 8: 127.0, 16: 32767.0}


def intn_wire_nbytes(n_elems: int, block: int = 256, bits: int = 8) -> int:
    """Exact wire footprint of one compressed tensor: carrier payload (int4
    nibble-packed into int8, int8, or int16 — always over the block-padded
    length) plus one fp32 scale per block. This is the SAME arithmetic the
    jaxpr schema pass (``analysis/collectives.py``) recovers from the operand
    avals, so spans recorded with it cross-check exactly."""
    n_pad = -(-n_elems // block) * block
    payload = {4: n_pad // 2, 8: n_pad, 16: 2 * n_pad}[bits]
    return payload + (n_pad // block) * 4


def intn_blockwise_compress(flat: jnp.ndarray, block: int = 256,
                            bits: int = 8) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(n,) f32 → (carrier, scales f32 (n_pad/block,)); symmetric absmax per
    block (``scale = absmax/qmax``, zero blocks get scale 1). Carrier: int8
    (n_pad,) for bits=8, int16 (n_pad,) for bits=16, adjacent-pair
    nibble-packed int8 (n_pad/2,) for bits=4 (``block`` must be even)."""
    qmax = _WIRE_QMAX[bits]
    n = flat.shape[0]
    pad = (-n) % block
    fb = jnp.pad(flat, (0, pad)).reshape(-1, block)
    amax = jnp.max(jnp.abs(fb), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(fb / scale), -qmax, qmax)
    if bits == 16:
        return q.astype(jnp.int16).reshape(-1), scale[:, 0]
    q = q.astype(jnp.int8).reshape(-1)
    if bits == 4:
        # two nibbles per byte, adjacent pairs (n_pad is even: block is);
        # arithmetic >> sign-extends on unpack, same idiom as quant.pack_int4
        half = q.reshape(-1, 2)
        q = ((half[:, 1] << 4) | (half[:, 0] & 0xF)).astype(jnp.int8)
    return q, scale[:, 0]


def intn_blockwise_decompress(q: jnp.ndarray, scales: jnp.ndarray, n: int,
                              block: int = 256, bits: int = 8) -> jnp.ndarray:
    """Inverse of :func:`intn_blockwise_compress` (drops the pad)."""
    if bits == 4:
        lo = ((q << 4) >> 4).astype(jnp.int8)
        hi = (q >> 4).astype(jnp.int8)
        q = jnp.stack([lo, hi], axis=1).reshape(-1)
    fb = q.reshape(-1, block).astype(jnp.float32) * scales[:, None]
    return fb.reshape(-1)[:n]


def int8_blockwise_compress(flat: jnp.ndarray, block: int = 256
                            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(n,) f32 → (q int8 (n_pad,), scales f32 (n_pad/block,)); the bits=8
    specialisation of :func:`intn_blockwise_compress` (kept as the named
    EQuARX wire the 1-bit machinery composes with)."""
    return intn_blockwise_compress(flat, block, 8)


def int8_blockwise_decompress(q: jnp.ndarray, scales: jnp.ndarray, n: int,
                              block: int = 256) -> jnp.ndarray:
    """Inverse of :func:`int8_blockwise_compress` (drops the pad)."""
    return intn_blockwise_decompress(q, scales, n, block, 8)


def quantized_allreduce(x: jnp.ndarray, error: jnp.ndarray, axis_name: str,
                        block: int = 256, bits: int = 8
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    with named_scope("comm.quantized_allreduce"):
        return _quantized_allreduce(x, error, axis_name, block, bits)


def _quantized_allreduce(x: jnp.ndarray, error: jnp.ndarray, axis_name: str,
                         block: int = 256, bits: int = 8
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Error-compensated intN blockwise mean over ``axis_name`` (call inside
    ``shard_map``); returns ``(replicated quantized mean, new local error)``.
    ``bits`` selects the wire width (:data:`WIRE_BITS`; default int8 = the
    original EQuARX wire).

    Two-phase, EQuARX-shaped, so per-worker wire volume stays O(n) at any
    world size (a naive gather-then-sum moves ``(W-1)·n`` — MORE than fp32
    beyond W≈8):

    1. **reduce-scatter phase**: each worker quantizes its contribution and
       ``all_to_all``s int8 chunk ``p`` (+ its scales) to worker ``p``, which
       dequantizes and sums its owned chunk in fixed rank order
       (deterministic);
    2. **gather phase**: the owned mean chunk is RE-quantized to int8 and
       ``all_gather``ed, so the wire stays 8-bit both ways.

    Both quantization stages are error-fed-back: phase 1 into this worker's
    residual everywhere, phase 2 (whose error is shared by construction) into
    the OWNED chunk's residual scaled by ``W`` — the next round's mean dilutes
    it back by ``1/W``, preserving the cumulative-transmission EF contract
    shared with :func:`compress_signs`.

    Non-finite inputs (fp16 overflow) are zeroed BEFORE quantization so a
    single inf cannot poison the int8 cast or the residual — the caller
    detects overflow from the pre-quantization values and skips the step.

    Collective volume per worker per phase: ``(W-1)/W ·
    intn_wire_nbytes(n)`` (intN payload + fp32 block scales) — at block=256
    that is ~7.8x/3.9x/2x under the full-precision ring allreduce
    (``8n·(W-1)/W``) for bits=4/8/16.
    """
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    err = error.reshape(-1).astype(jnp.float32)
    c = flat + err
    c = jnp.where(jnp.isfinite(c), c, 0.0)
    n = flat.shape[0]
    W = jax.lax.psum(1, axis_name)
    if W == 1:
        q, scales = intn_blockwise_compress(c, block, bits)
        deq = intn_blockwise_decompress(q, scales, n, block, bits)
        return deq.reshape(shape), (c - deq).reshape(shape)

    # pad so payload AND scale vectors split evenly across the W ranks
    n_pad = -((-n) // (block * W)) * (block * W)
    cp = jnp.pad(c, (0, n_pad - n))
    q, scales = intn_blockwise_compress(cp, block, bits)  # carrier, (n_pad/block,)
    chunk = n_pad // W
    bpc = (n_pad // block) // W                     # scale blocks per chunk
    # phase 1: rank p ends holding every rank's chunk p (intN on the wire;
    # the carrier splits evenly: chunk is a block multiple and block is even)
    qx = jax.lax.all_to_all(q.reshape(W, -1), axis_name, 0, 0, tiled=True)
    sx = jax.lax.all_to_all(scales.reshape(W, bpc), axis_name, 0, 0,
                            tiled=True)
    part = jax.vmap(
        lambda qq, ss: intn_blockwise_decompress(qq, ss, chunk, block, bits)
    )(qx, sx)
    mean_chunk = jnp.sum(part, axis=0) / W
    # phase 2: re-quantize the owned mean chunk, gather carrier + scales
    q2, s2 = intn_blockwise_compress(mean_chunk, block, bits)
    deq_chunk = intn_blockwise_decompress(q2, s2, chunk, block, bits)
    qg = jax.lax.all_gather(q2, axis_name, axis=0, tiled=True)
    sg = jax.lax.all_gather(s2, axis_name, axis=0, tiled=True)
    mean = intn_blockwise_decompress(qg, sg, n, block, bits)
    # error feedback: phase-1 everywhere, phase-2 at the owned chunk ×W
    r = cp - intn_blockwise_decompress(q, scales, n_pad, block, bits)
    idx = jax.lax.axis_index(axis_name)
    r = jax.lax.dynamic_update_slice(
        r, jax.lax.dynamic_slice(r, (idx * chunk,), (chunk,))
        + W * (mean_chunk - deq_chunk), (idx * chunk,))
    return mean.reshape(shape), r[:n].reshape(shape)


def compressed_allreduce(x: jnp.ndarray, error: jnp.ndarray,
                         axis_name: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-bit mean over ``axis_name`` (call inside ``shard_map``); returns
    ``(mean of compressed worker tensors, new local error)``.

    Collective volume: n/8 bytes of signs + 4 bytes of scale per worker (vs 4n bytes
    for a full fp32 allreduce).
    """
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    err = error.reshape(-1).astype(jnp.float32)
    packed, scale, new_error = compress_signs(flat, err)
    gathered = jax.lax.all_gather(packed, axis_name)      # (W, n/8) uint8
    scales = jax.lax.all_gather(scale, axis_name)         # (W,)
    n = flat.shape[0]
    signs = jax.vmap(lambda p: _unpack_bits(p, n))(gathered)  # (W, n) bool
    avg = jnp.mean(jnp.where(signs, scales[:, None], -scales[:, None]), axis=0)
    return avg.reshape(shape), new_error.reshape(shape)
