"""Reference-format checkpoint tools (import/export Megatron-DeepSpeed runs).

Counterpart of ``deepspeed/checkpoint/``: :class:`DeepSpeedCheckpoint` inspects a
3D (pp × tp × dp) training checkpoint folder, merges tensor-parallel shards, rebuilds
fp32 weights from ZeRO optimizer shards, and converts Megatron-GPT trees into this
framework's :mod:`~deepspeed_tpu.models.causal_lm` parameters. THIS framework's own
checkpoints need none of this — orbax arrays re-shard to any mesh on restore.
The export direction (:func:`export_universal_checkpoint`,
:func:`export_fp32_state_dict`) writes a trained engine back out in the reference's
universal / zero_to_fp32 formats for torch-side consumption.
"""

from .constants import *  # noqa: F401,F403
from .deepspeed_checkpoint import (DeepSpeedCheckpoint, merge_tp_shards,  # noqa: F401
                                   split_megatron_qkv, to_causal_lm_params)
from .export import (export_fp32_state_dict,  # noqa: F401
                     export_universal_checkpoint)
from .reshape import (Model3DDescriptor, get_model_3d_descriptor,  # noqa: F401
                      get_zero_files, reshape_3d, reshape_meg_2d_parallel)
