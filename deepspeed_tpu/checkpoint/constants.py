"""Reference checkpoint naming/key constants.

Mirrors ``deepspeed/checkpoint/constants.py`` — these are the on-disk compatibility
surface of DeepSpeed/Megatron training checkpoints (file prefixes and state-dict keys),
so they are kept verbatim-compatible.
"""

# optimizer checkpoint keys
OPTIMIZER_STATE_DICT = "optimizer_state_dict"
BASE_OPTIMIZER_STATE = "base_optimizer_state"
SINGLE_PARTITION_OF_FP32_GROUPS = "single_partition_of_fp32_groups"
GROUP_PADDINGS = "group_paddings"
PARTITION_COUNT = "partition_count"
ZERO_STAGE = "zero_stage"

# module checkpoint keys
PARAM_SHAPES = "param_shapes"
BUFFER_NAMES = "buffer_names"
ITERATION_KEY = "iteration"
ARGS_KEY = "args"

# checkpoint file naming
MODEL_FILE_PREFIX = "mp_rank_"
ZERO_FILE_PREFIX = "zero_pp_rank_"
LAYER_FILE_PREFIX = "layer_"
OPTIM_FILE_SUFFIX = "_optim_states.pt"
MODEL_FILE_SUFFIX = "_model_states.pt"
BF16_ZERO_FILE_PREFIX = "bf16_" + ZERO_FILE_PREFIX
FP16_ZERO_FILE_PREFIX = "fp16_" + ZERO_FILE_PREFIX

DS_VERSION = "ds_version"
