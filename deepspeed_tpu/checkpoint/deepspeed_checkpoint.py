"""Reference-format checkpoint importer (Megatron-DeepSpeed 3D training checkpoints).

TPU-native re-design of ``deepspeed/checkpoint/deepspeed_checkpoint.py``: the reference
class answers "which files does new rank (pp, tp, dp) read" for a torch resume; here the
importer's job is to get a reference run's weights INTO this framework — merge the
``layer_*-model_*`` / ``mp_rank_*`` tensor-parallel shards into full numpy tensors
(column/row/replicated policy per Megatron name), optionally reconstruct fp32 weights
from ``zero_pp_rank_*`` optimizer shards (``utils/zero_to_fp32.py`` semantics for
REFERENCE files), and convert to a :mod:`deepspeed_tpu.models.causal_lm` parameter tree.
Any mesh placement afterwards is the engine's business (orbax re-shards on restore), so
no torch-side reshape machinery is needed.

Files are read lazily one at a time — peak host memory is one shard + the merged result.
"""

import os
import re
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.logging import logger
from .constants import (ARGS_KEY, BASE_OPTIMIZER_STATE, GROUP_PADDINGS,
                        ITERATION_KEY, LAYER_FILE_PREFIX, MODEL_FILE_PREFIX,
                        OPTIMIZER_STATE_DICT, PARAM_SHAPES, PARTITION_COUNT,
                        SINGLE_PARTITION_OF_FP32_GROUPS, ZERO_STAGE)
from .reshape import (Model3DDescriptor, get_model_3d_descriptor, get_zero_files,
                      reshape_3d, _files, _with_prefix)

# Megatron tensor-parallel merge policy (reference deepspeed_checkpoint.py:26-36):
# names matching these suffixes are replicated across tp ranks (take rank 0);
# listed weights concatenate on dim 1 (row-parallel); everything else on dim 0.
SEQUENTIAL_LAYERS = [
    "input_layernorm.weight", "input_layernorm.bias",
    "self_attention.dense.bias", "attention.dense.bias",
    "post_attention_layernorm.weight", "post_attention_layernorm.bias",
    "mlp.dense_4h_to_h.bias",
    "position_embeddings.weight",
    "final_layernorm.weight", "final_layernorm.bias",
]
LAYER_CONCAT_DIM = {"self_attention.dense.weight": 1, "attention.dense.weight": 1,
                    "mlp.dense_4h_to_h.weight": 1}


def _torch_load(path: str) -> Dict[str, Any]:
    import torch
    return torch.load(path, map_location="cpu", weights_only=False)


def _np(t) -> np.ndarray:
    import torch
    if isinstance(t, torch.Tensor):
        return t.detach().to(torch.float32).numpy()
    return np.asarray(t)


def merge_tp_shards(name: str, shards: List[np.ndarray]) -> np.ndarray:
    """Merge one parameter's tensor-parallel shards per the Megatron policy."""
    # the final-layernorm layer file stores BARE "weight"/"bias" (the module's own
    # state dict) — replicated, like the dotted norm names below
    if name in ("weight", "bias") or any(name.endswith(s)
                                         for s in SEQUENTIAL_LAYERS):
        return shards[0]
    if len(shards) == 1:
        return shards[0]
    for suffix, dim in LAYER_CONCAT_DIM.items():
        if name.endswith(suffix):
            return np.concatenate(shards, axis=dim)
    return np.concatenate(shards, axis=0)


class DeepSpeedCheckpoint:
    """Inspect + import a reference-format 3D checkpoint directory.

    ``tp_degree``/``pp_degree``/``dp_degree`` request a target topology for
    rank-file mapping queries (contraction only, like the reference); tensor merging
    always produces FULL tensors regardless.
    """

    def __init__(self, dir: str, tp_degree: Optional[int] = None,
                 pp_degree: Optional[int] = None, dp_degree: Optional[int] = None):
        if not (os.path.isdir(dir)):
            raise AssertionError(f"{dir} is not a checkpoint folder")
        self.dir = dir
        self.file_list = _files(dir)
        self.zero_files = get_zero_files(dir)
        self.layer_files = _with_prefix(self.file_list, LAYER_FILE_PREFIX)
        self.mp_rank_files = _with_prefix(self.file_list, MODEL_FILE_PREFIX)
        self.src_3d = get_model_3d_descriptor(dir)
        self.tp_degree = tp_degree or self.src_3d.tp_degree
        self.pp_degree = pp_degree or max(self.src_3d.pp_degree, 1)
        self.dp_degree = dp_degree or self.src_3d.dp_degree
        self.original_world_size = self.src_3d.world_size()
        self.world_size = self.tp_degree * self.pp_degree * self.dp_degree
        self.layer_keys = self._layer_keys()
        self.layer_count = len(self.layer_keys)
        self._file_map = None
        if self.src_3d.pp_degree > 0:
            self._file_map = reshape_3d(
                Model3DDescriptor(max(self.src_3d.pp_degree, 1),
                                  self.src_3d.tp_degree, self.src_3d.dp_degree),
                Model3DDescriptor(self.pp_degree, self.tp_degree, self.dp_degree))
        self.global_state: Dict[str, Any] = {}

    # ------------------------------------------------------------------ census
    def _layer_keys(self) -> List[str]:
        # numeric sort: 'layer_100' must come after 'layer_99' (lexical order would
        # silently scramble deep models — same hazard reshape._natural_key guards)
        ids = sorted({m.group(1) for f in self.layer_files
                      for m in [re.match(rf"{LAYER_FILE_PREFIX}(\d+)-",
                                         os.path.basename(f))] if m}, key=int)
        return ids

    def layer_shards(self, layer_key: str) -> List[str]:
        return sorted(f for f in self.layer_files
                      if os.path.basename(f).startswith(
                          f"{LAYER_FILE_PREFIX}{layer_key}-"))

    def get_files_for_rank(self, pp_index: int, tp_index: int,
                           dp_index: int = 0) -> List[str]:
        """ZeRO optim files the given NEW-topology rank must merge (reference
        ``ZeROCheckpoint.get_files_for_rank``)."""
        if not (self._file_map is not None):
            raise AssertionError("no pipeline layout in this checkpoint")
        idxs = self._file_map[dp_index][(pp_index, tp_index)]
        return [self.zero_files[i] for i in idxs]

    # ------------------------------------------------------------------ global state
    def _build_global_state(self):
        if self.global_state or not self.mp_rank_files:
            return
        sd = _torch_load(self.mp_rank_files[0])
        self.global_state[ITERATION_KEY] = sd.get(ITERATION_KEY, 0)
        self.global_state[ARGS_KEY] = sd.get(ARGS_KEY, None)

    def get_iteration(self) -> int:
        self._build_global_state()
        return self.global_state.get(ITERATION_KEY, 0)

    def get_args(self):
        self._build_global_state()
        return self.global_state.get(ARGS_KEY)

    # ------------------------------------------------------------------ tensor merge
    def merged_layer_state(self, layer_key: str) -> Dict[str, np.ndarray]:
        """One sequential layer's full tensors: load its tp shard files, merge."""
        shards = [_torch_load(f) for f in self.layer_shards(layer_key)]
        if not (shards):
            raise AssertionError(f"no files for layer {layer_key!r}")
        out = {}
        for name in shards[0]:
            vals = [_np(s[name]) for s in shards]
            out[name] = merge_tp_shards(name, vals)
        return out

    def merged_state_dict(self) -> Dict[str, np.ndarray]:
        """All layers, keys prefixed ``<layer_key>.<param>`` (Megatron sequential
        numbering); for non-pipeline checkpoints, the merged ``mp_rank_*`` module
        state instead."""
        if self.layer_keys:
            out = {}
            for lk in self.layer_keys:
                for name, v in self.merged_layer_state(lk).items():
                    out[f"{lk}.{name}"] = v
            return out
        shards = []
        for f in self.mp_rank_files:
            sd = _torch_load(f)
            shards.append(sd.get("module", sd))
        flat = [_flatten_module(s) for s in shards]
        return {name: merge_tp_shards(name, [f[name] for f in flat])
                for name in flat[0]}

    # ------------------------------------------------------------------ zero → fp32
    def reconstruct_fp32_state_dict(self) -> Dict[str, np.ndarray]:
        """Rebuild full fp32 weights from ``zero_pp_rank_*`` optimizer shards
        (reference ``utils/zero_to_fp32.py`` for stage 1/2 files): concatenate each
        param group's per-dp flat partitions, trim padding, split per the
        ``param_shapes`` recorded in the matching ``mp_rank_*`` model file."""
        if not (self.zero_files):
            raise AssertionError("no zero_pp_rank_* files in this checkpoint")
        if not (self.mp_rank_files):
            raise AssertionError("need mp_rank_* model files for param_shapes")
        model_sd = _torch_load(self.mp_rank_files[0])
        param_shapes = model_sd[PARAM_SHAPES]
        if isinstance(param_shapes, dict):
            param_shapes = [param_shapes]
        opt_sds = [_torch_load(f)[OPTIMIZER_STATE_DICT] for f in self.zero_files]
        stage = opt_sds[0].get(ZERO_STAGE, 1)
        # the reference records group_paddings per-rank and only the LAST dp rank's
        # partition is padded (stage_1_and_2.py:333-339 sets 0 for all earlier
        # ranks), so the concatenated flat group's trailing pad lives in the last
        # shard — read the paddings from there
        paddings = opt_sds[-1].get(GROUP_PADDINGS,
                                   [0] * len(param_shapes))
        out: Dict[str, np.ndarray] = {}
        for gi, group_shapes in enumerate(param_shapes):
            flat = np.concatenate(
                [_np(sd[SINGLE_PARTITION_OF_FP32_GROUPS][gi]).reshape(-1)
                 for sd in opt_sds])
            if paddings and gi < len(paddings) and paddings[gi]:
                flat = flat[:-paddings[gi]] if paddings[gi] > 0 else flat
            offset = 0
            for name, shape in group_shapes.items():
                n = int(np.prod(shape))
                if not (offset + n <= flat.size):
                    raise AssertionError(f"group {gi} underflow at {name} (stage {stage})")
                out[name] = flat[offset:offset + n].reshape(tuple(shape))
                offset += n
            if offset != flat.size:
                logger.warning(f"group {gi}: {flat.size - offset} trailing elements "
                               "unclaimed (alignment padding)")
        return out


def _flatten_module(sd: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in sd.items():
        if isinstance(v, dict):
            out.update(_flatten_module(v, prefix + str(k) + "."))
        else:
            try:
                out[prefix + str(k)] = _np(v)
            except Exception:
                continue
    return out


# ---------------------------------------------------------------- Megatron → CausalLM
def split_megatron_qkv(qkv: np.ndarray, n_head: int):
    """Split a Megatron fused query_key_value weight/bias into q, k, v.

    Megatron interleaves per attention head: rows ordered [head, (q|k|v), head_dim]
    (reference ``megatron/model/transformer.py`` fused QKV; the containers undo this in
    ``module_inject/containers/megatron_gpt.py``)."""
    three_h = qkv.shape[0]
    if not (three_h % (3 * n_head) == 0):
        raise AssertionError((qkv.shape, n_head))
    hn = three_h // (3 * n_head)
    parts = qkv.reshape(n_head, 3, hn, *qkv.shape[1:])
    q, k, v = (parts[:, i].reshape(n_head * hn, *qkv.shape[1:]) for i in range(3))
    return q, k, v


def to_causal_lm_params(ckpt: "DeepSpeedCheckpoint", n_head: int,
                        n_layer: Optional[int] = None) -> Dict[str, Any]:
    """Convert a merged Megatron-GPT checkpoint into a
    :class:`~deepspeed_tpu.models.causal_lm.CausalLM` parameter tree (torch (out, in)
    weights transposed to flax (in, out) kernels; fused QKV de-interleaved).

    Layer-key convention (Megatron sequential numbering): the embedding layer holds
    ``word_embeddings.weight``/``position_embeddings.weight``, transformer layers hold
    ``input_layernorm``/``self_attention``/``mlp`` blocks, the final layer holds the
    closing layernorm.
    """
    merged = ckpt.merged_state_dict()
    tree: Dict[str, Any] = {}
    layer_ids = sorted({k.split(".")[0] for k in merged},
                       key=lambda s: int(s) if s.isdigit() else 10**9)
    transformer_idx = 0
    for lid in layer_ids:
        names = {k[len(lid) + 1:]: v for k, v in merged.items()
                 if k.startswith(lid + ".")}
        if "word_embeddings.weight" in names:
            tree["wte"] = names["word_embeddings.weight"]
            if "position_embeddings.weight" in names:
                tree["wpe"] = names["position_embeddings.weight"]
            continue
        if "input_layernorm.weight" in names:      # transformer block
            qkv_w = names.get("self_attention.query_key_value.weight",
                              names.get("attention.query_key_value.weight"))
            qw, kw, vw = split_megatron_qkv(qkv_w, n_head)
            layer = {
                "ln_attn": {"scale": names["input_layernorm.weight"],
                            "bias": names["input_layernorm.bias"]},
                "q_proj": {"kernel": qw.T},
                "k_proj": {"kernel": kw.T},
                "v_proj": {"kernel": vw.T},
                "o_proj": {"kernel": names.get(
                    "self_attention.dense.weight",
                    names.get("attention.dense.weight")).T},
                "ln_mlp": {"scale": names["post_attention_layernorm.weight"],
                           "bias": names["post_attention_layernorm.bias"]},
                "fc_in": {"kernel": names["mlp.dense_h_to_4h.weight"].T},
                "fc_out": {"kernel": names["mlp.dense_4h_to_h.weight"].T},
            }
            qkv_b = names.get("self_attention.query_key_value.bias",
                              names.get("attention.query_key_value.bias"))
            if qkv_b is not None:
                qb, kb, vb = split_megatron_qkv(qkv_b, n_head)
                layer["q_proj"]["bias"] = qb
                layer["k_proj"]["bias"] = kb
                layer["v_proj"]["bias"] = vb
            for mega, ours in [("self_attention.dense.bias", "o_proj"),
                               ("attention.dense.bias", "o_proj"),
                               ("mlp.dense_h_to_4h.bias", "fc_in"),
                               ("mlp.dense_4h_to_h.bias", "fc_out")]:
                if mega in names:
                    layer[ours]["bias"] = names[mega]
            tree[f"layers_{transformer_idx}"] = layer
            transformer_idx += 1
            continue
        if "weight" in names and names["weight"].ndim == 1:   # final layernorm
            tree["ln_f"] = {"scale": names["weight"], "bias": names["bias"]}
    if n_layer is not None:
        if not (transformer_idx == n_layer):
            raise AssertionError(f"checkpoint has {transformer_idx} transformer layers, expected {n_layer}")
    return tree
