"""Checkpoint topology descriptors and rank-file reshape maps.

TPU-native re-design of ``deepspeed/checkpoint/reshape_meg_2d.py`` /
``reshape_3d_utils.py``: where the reference builds string-keyed map objects through
stacked helper classes, the same math here is one dict comprehension per transform —
a (pp, tp) cell of the NEW topology maps to the list of OLD rank indices whose shards it
must merge, with the dp dimension partitioned on top. Only degree-contraction is
supported (e.g. tp 4→2), like the reference.

The actual tensor resharding on TPU is a non-event — the engine restores any merged tree
into whatever mesh is active (orbax re-shards) — so these maps exist to drive FILE
reading of reference checkpoints, not device placement.
"""

import dataclasses
import os
import re
from typing import Dict, List, Tuple

from .constants import (BF16_ZERO_FILE_PREFIX, FP16_ZERO_FILE_PREFIX,
                        LAYER_FILE_PREFIX, MODEL_FILE_PREFIX, ZERO_FILE_PREFIX)


def _partition(lst: List, n: int) -> List[List]:
    if not (len(lst) % n == 0):
        raise AssertionError(f"cannot partition {len(lst)} items into {n}")
    sz = len(lst) // n
    return [lst[i * sz:(i + 1) * sz] for i in range(n)]


@dataclasses.dataclass(frozen=True)
class Model3DDescriptor:
    """Source topology of a reference checkpoint (``model_3d_desc``)."""
    pp_degree: int
    tp_degree: int
    dp_degree: int

    def world_size(self) -> int:
        return max(self.pp_degree, 1) * self.tp_degree * self.dp_degree

    def can_reshape(self, other: "Model3DDescriptor") -> Tuple[bool, List[str]]:
        errs = [f"Expansion reshape not supported - {dim}: {old} ---> {new}"
                for dim, old, new in [("PP", self.pp_degree, other.pp_degree),
                                      ("TP", self.tp_degree, other.tp_degree),
                                      ("DP", self.dp_degree, other.dp_degree)]
                if new > old]
        return not errs, errs


def reshape_meg_2d_parallel(old_pp: int, old_tp: int, new_pp: int, new_tp: int
                            ) -> Dict[Tuple[int, int], List[int]]:
    """(new_pp_idx, new_tp_idx) → ordered old 2D rank indices to merge.

    Old rank layout is row-major (pp major, tp minor), as Megatron numbers them;
    contracting tp by r merges r consecutive tp ranks, contracting pp by r merges r
    consecutive pp rows — the same grouping ``reshape_meg_2d.py`` produces.
    """
    if not (old_pp % new_pp == 0 and old_tp % new_tp == 0):
        raise AssertionError(f"degrees must contract evenly: pp {old_pp}->{new_pp}, tp {old_tp}->{new_tp}")
    # start from the identity map, contract tp, then pp
    cells = {(p, t): [p * old_tp + t] for p in range(old_pp) for t in range(old_tp)}
    if new_tp != old_tp:
        cells = {(p, tj): sum((cells[(p, t)] for t in row), [])
                 for p in range(old_pp)
                 for tj, row in enumerate(_partition(list(range(old_tp)), new_tp))}
    if new_pp != old_pp:
        cells = {(pj, t): sum((cells[(p, t)] for p in col), [])
                 for t in range(new_tp)
                 for pj, col in enumerate(_partition(list(range(old_pp)), new_pp))}
    return cells


def reshape_3d(src: Model3DDescriptor, dst: Model3DDescriptor
               ) -> List[Dict[Tuple[int, int], List[int]]]:
    """Per-new-dp-index 2D maps of GLOBAL old rank indices (``model_3d_desc.reshape``).

    Old global rank = dp_index * (pp*tp) + 2d_index (dp outermost, matching the
    reference's ``flatten_dp_dimension``)."""
    ok, errs = src.can_reshape(dst)
    if not (ok):
        raise AssertionError(",".join(errs))
    base = reshape_meg_2d_parallel(src.pp_degree, src.tp_degree,
                                   dst.pp_degree, dst.tp_degree)
    plane = src.pp_degree * src.tp_degree
    out = []
    for dp_group in _partition(list(range(src.dp_degree)), dst.dp_degree):
        out.append({cell: [dp * plane + idx for dp in dp_group for idx in idxs]
                    for cell, idxs in base.items()})
    return out


# --------------------------------------------------------------------- folder scan
def _natural_key(path: str):
    """Sort key treating digit runs numerically: zero_pp_rank_10 sorts AFTER
    zero_pp_rank_9 (lexical order would scramble dp ranks >= 10 and silently
    corrupt partition concatenation — reference zero_to_fp32.py sorts the same way)."""
    return [int(tok) if tok.isdigit() else tok
            for tok in re.split(r"(\d+)", os.path.basename(path))]


def _files(dir: str) -> List[str]:
    out = []
    for root, _, files in os.walk(dir):
        out.extend(os.path.join(root, f) for f in files)
    return sorted(out, key=_natural_key)


def _with_prefix(files: List[str], prefix: str) -> List[str]:
    return sorted((f for f in files if os.path.basename(f).startswith(prefix)),
                  key=_natural_key)


def get_zero_files(dir: str) -> List[str]:
    files = _files(dir)
    for prefix in (ZERO_FILE_PREFIX, FP16_ZERO_FILE_PREFIX, BF16_ZERO_FILE_PREFIX):
        zf = _with_prefix(files, prefix)
        if zf:
            return zf
    return []


def get_model_3d_descriptor(dir: str) -> Model3DDescriptor:
    """Infer (pp, tp, dp) from the checkpoint's file census — same inference as
    reference ``get_model_3d_descriptor`` (layer files ⇒ pipeline-style layout)."""
    files = _files(dir)
    zero_files = get_zero_files(dir)
    mp_files = _with_prefix(files, MODEL_FILE_PREFIX)
    # tp degree = number of model shards of the first layer file, if layers exist
    layer_ids = sorted({m.group(1) for f in files
                        for m in [re.match(rf"{LAYER_FILE_PREFIX}(\d+)-model_",
                                           os.path.basename(f))] if m})
    if layer_ids:
        tp = len([f for f in files if os.path.basename(f).startswith(
            f"{LAYER_FILE_PREFIX}{layer_ids[0]}-model_")])
        pp = len(mp_files) // max(tp, 1)
        dp = max(1, len(zero_files) // max(pp * tp, 1))
        return Model3DDescriptor(pp_degree=pp, tp_degree=tp, dp_degree=dp)
    tp = len(mp_files)
    dp = max(1, len(zero_files) // max(tp, 1))
    return Model3DDescriptor(pp_degree=0, tp_degree=tp, dp_degree=dp)
