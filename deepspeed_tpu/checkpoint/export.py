"""Checkpoint EXPORT to reference-consumable formats — the other half of interop.

Round 3 shipped the import direction (``deepspeed_checkpoint.py`` reads Megatron
``layer_*``/``mp_rank_*``/``zero_pp_rank_*`` files); this module writes a trained
engine's state OUT so a run can migrate back to torch tooling:

- :func:`export_universal_checkpoint` — the reference *universal checkpoint* layout
  (``zero/<param_name>/{fp32,exp_avg,exp_avg_sq}.pt``, each ``{"param": tensor}`` —
  the exact per-file contract ``universal_checkpoint.py:load_hp_checkpoint_state``
  consumes, reference ``checkpoint/universal_checkpoint.py:108``), plus an
  ``mp_rank_00_model_states.pt`` with the module weights and ``param_shapes`` so
  this framework's own importer (and Megatron-style loaders) re-read it.
- :func:`export_fp32_state_dict` — one consolidated ``pytorch_model.bin``
  (``utils/zero_to_fp32.py:483``'s output format: a flat torch state dict of fp32
  weights, loadable by ``model.load_state_dict`` in torch land).

Works for both engine modes: the resident fused engine (fp32 masters + AdamState
moments in ``state``) and the param-offload coordinator (host/NVMe masters +
CPU-Adam or NVMe moments). Multi-process partitioned offload exports per-rank
state only through its own partition files; consolidate on one process first.
"""

import os
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..utils.logging import logger

PARAM = "param"
CAT_DIM = "cat_dim"
FP32_NAME = "fp32"
EXP_AVG = "exp_avg"
EXP_AVG_SQ = "exp_avg_sq"


def _dotted_tree(tree, prefix="") -> Dict[str, np.ndarray]:
    """Flatten a nested param dict to reference-style dotted names → fp32 arrays."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(_dotted_tree(v, key))
        return out
    if isinstance(tree, (tuple, list)):
        for i, v in enumerate(tree):
            key = f"{prefix}.{i}" if prefix else str(i)
            out.update(_dotted_tree(v, key))
        return out
    # private writable copy: jax/np views of device memory are read-only and
    # torch.from_numpy refuses (warns on) non-writable buffers
    out[prefix] = np.array(tree, dtype=np.float32, copy=True)
    return out


def _gather_engine_state(engine) -> Tuple[Dict[str, np.ndarray],
                                          Optional[Dict[str, np.ndarray]],
                                          Optional[Dict[str, np.ndarray]],
                                          int]:
    """(fp32 params, exp_avg, exp_avg_sq, step) as dotted-name dicts."""
    if getattr(engine, "param_offload_enabled", False):
        co = engine._param_offload
        if co._partitioned:
            raise NotImplementedError(
                "universal export of a multi-process partitioned offload run: "
                "each process holds only its master shards — resume "
                "single-process from the partition checkpoint and export there")
        params = _dotted_tree(co.full_params_host())
        # flat moments follow the coordinator's global leaf order, which is also
        # the leaf order of full_params_host's flattening
        if co.nvme is not None:
            ms, vs = co.nvme.read_moments()
            step = int(co.step_count)
        elif co.kind in ("adam", "adamw"):
            sd = co.opt.state_dict()
            ms, vs, step = sd["m"], sd["v"], int(sd["step"])
        else:
            ms = vs = None
            step = int(co.step_count)
        m_named = v_named = None
        if ms is not None:
            names = list(params.keys())
            assert len(names) == len(ms)
            m_named = {n: np.asarray(m, np.float32).reshape(params[n].shape)
                       for n, m in zip(names, ms)}
            v_named = {n: np.asarray(v, np.float32).reshape(params[n].shape)
                       for n, v in zip(names, vs)}
        return params, m_named, v_named, step

    import jax
    state = engine.state
    step = int(getattr(engine, "global_steps", 0))
    if getattr(engine, "offload_enabled", False):
        # ZeRO-Offload: the fp32 MASTERS live in the host tier (device params are
        # compute-dtype-rounded copies), and so do the Adam moments
        tier = engine._offload_tier
        if getattr(tier, "_partitioned", False):
            raise NotImplementedError(
                "universal export of a multi-process partitioned offload run: "
                "each process holds only its master shards — resume "
                "single-process from the partition checkpoint and export there")
        leaves, treedef = jax.tree_util.tree_flatten(state.params)
        shapes = [tuple(l.shape) for l in leaves]
        tree = jax.tree_util.tree_unflatten(
            treedef, [np.asarray(m, np.float32).reshape(s)
                      for m, s in zip(tier.masters, shapes)])
        params = _dotted_tree(tree)
        names = list(params.keys())        # == tree-flatten leaf order
        m_named = v_named = None
        if tier.nvme is not None:
            ms, vs = tier.nvme.read_moments()
        elif tier.kind == "adam":
            sd = tier.opt.state_dict()
            ms, vs = sd["m"], sd["v"]
        else:
            ms = vs = None
            logger.warning(
                f"universal export: optimizer kind {tier.kind!r} has no "
                "exp_avg/exp_avg_sq — the checkpoint carries weights only and a "
                "torch-side resume restarts optimizer state from zero")
        if ms is not None:
            assert len(names) == len(ms)
            m_named = {n: np.asarray(m, np.float32).reshape(params[n].shape)
                       for n, m in zip(names, ms)}
            v_named = {n: np.asarray(v, np.float32).reshape(params[n].shape)
                       for n, v in zip(names, vs)}
        return params, m_named, v_named, step

    # _dotted_tree already makes the fp32 host copy per leaf — no outer tree_map
    # (that would transiently double host RAM on large models)
    params = _dotted_tree(state.params)
    m_named = v_named = None
    opt = state.opt_state
    if hasattr(opt, "exp_avg") and hasattr(opt, "exp_avg_sq"):
        # note: iteration stays engine.global_steps, NOT opt.step — fp16
        # overflow-skipped steps advance the former but not the latter, and a
        # torch-side resume schedules LR/data off the training iteration
        m_named = _dotted_tree(opt.exp_avg)
        v_named = _dotted_tree(opt.exp_avg_sq)
    else:
        logger.warning(
            "universal export: optimizer state has no exp_avg/exp_avg_sq — the "
            "checkpoint carries weights only and a torch-side resume restarts "
            "optimizer state from zero")
    return params, m_named, v_named, step


def _unflatten(named: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Dotted names back to a nested dict (for the mp_rank module payload)."""
    root: Dict[str, Any] = {}
    for name, arr in named.items():
        parts = name.split(".")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def export_universal_checkpoint(engine, save_dir: str,
                                tag: str = "universal") -> str:
    """Write the engine's state as a reference universal checkpoint.

    Layout under ``save_dir/tag``::

        zero/<param_name>/fp32.pt         {"param": fp32 tensor, "cat_dim": 0}
        zero/<param_name>/exp_avg.pt      (when Adam moments exist)
        zero/<param_name>/exp_avg_sq.pt
        mp_rank_00_model_states.pt        module weights + param_shapes + iteration
        latest_universal                  tag pointer

    Returns the checkpoint path.
    """
    import torch

    params, m_named, v_named, step = _gather_engine_state(engine)
    path = os.path.join(save_dir, str(tag))
    zero_dir = os.path.join(path, "zero")
    os.makedirs(zero_dir, exist_ok=True)

    for name, arr in params.items():
        pdir = os.path.join(zero_dir, name)
        os.makedirs(pdir, exist_ok=True)
        torch.save({PARAM: torch.from_numpy(np.ascontiguousarray(arr)),
                    CAT_DIM: 0}, os.path.join(pdir, f"{FP32_NAME}.pt"))
        if m_named is not None and name in m_named:
            torch.save({PARAM: torch.from_numpy(
                np.ascontiguousarray(m_named[name])), CAT_DIM: 0},
                os.path.join(pdir, f"{EXP_AVG}.pt"))
            torch.save({PARAM: torch.from_numpy(
                np.ascontiguousarray(v_named[name])), CAT_DIM: 0},
                os.path.join(pdir, f"{EXP_AVG_SQ}.pt"))

    module = _unflatten({n: torch.from_numpy(np.ascontiguousarray(a))
                         for n, a in params.items()})
    shapes = OrderedDict((n, tuple(a.shape)) for n, a in params.items())
    torch.save({"module": module, "param_shapes": shapes, "iteration": step,
                "dp_world_size": 1, "mp_world_size": 1},
               os.path.join(path, "mp_rank_00_model_states.pt"))
    with open(os.path.join(save_dir, "latest_universal"), "w") as f:
        f.write(str(tag))
    logger.info(f"universal checkpoint exported to {path} "
                f"({len(params)} params, step {step})")
    return path


def export_fp32_state_dict(engine, out_file: str) -> Dict[str, Any]:
    """Consolidated fp32 weights as one torch state dict file
    (``zero_to_fp32.py``'s ``pytorch_model.bin`` output format)."""
    import torch

    params, _, _, _ = _gather_engine_state(engine)
    sd = OrderedDict((n, torch.from_numpy(np.ascontiguousarray(a)))
                     for n, a in params.items())
    os.makedirs(os.path.dirname(os.path.abspath(out_file)), exist_ok=True)
    torch.save(sd, out_file)
    logger.info(f"fp32 state dict ({len(sd)} tensors) written to {out_file}")
    return sd
