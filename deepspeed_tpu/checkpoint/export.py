"""Checkpoint EXPORT to reference-consumable formats — the other half of interop.

Round 3 shipped the import direction (``deepspeed_checkpoint.py`` reads Megatron
``layer_*``/``mp_rank_*``/``zero_pp_rank_*`` files); this module writes a trained
engine's state OUT so a run can migrate back to torch tooling:

- :func:`export_universal_checkpoint` — the reference *universal checkpoint* layout
  (``zero/<param_name>/{fp32,exp_avg,exp_avg_sq}.pt``, each ``{"param": tensor}`` —
  the exact per-file contract ``universal_checkpoint.py:load_hp_checkpoint_state``
  consumes, reference ``checkpoint/universal_checkpoint.py:108``), plus an
  ``mp_rank_00_model_states.pt`` with the module weights and ``param_shapes`` so
  this framework's own importer (and Megatron-style loaders) re-read it.
- :func:`export_fp32_state_dict` — one consolidated ``pytorch_model.bin``
  (``utils/zero_to_fp32.py:483``'s output format: a flat torch state dict of fp32
  weights, loadable by ``model.load_state_dict`` in torch land).

Works for both engine modes: the resident fused engine (fp32 masters + AdamState
moments in ``state``) and the param-offload coordinator (host/NVMe masters +
CPU-Adam or NVMe moments). Multi-process partitioned offload exports per-rank
state only through its own partition files; consolidate on one process first.
"""

import os
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..utils.logging import logger

PARAM = "param"
CAT_DIM = "cat_dim"
FP32_NAME = "fp32"
EXP_AVG = "exp_avg"
EXP_AVG_SQ = "exp_avg_sq"


def _dotted_tree(tree, prefix="") -> Dict[str, np.ndarray]:
    """Flatten a nested param dict to reference-style dotted names → fp32 arrays."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(_dotted_tree(v, key))
        return out
    if isinstance(tree, (tuple, list)):
        for i, v in enumerate(tree):
            key = f"{prefix}.{i}" if prefix else str(i)
            out.update(_dotted_tree(v, key))
        return out
    # private writable copy: jax/np views of device memory are read-only and
    # torch.from_numpy refuses (warns on) non-writable buffers
    out[prefix] = np.array(tree, dtype=np.float32, copy=True)
    return out


def _gather_engine_state(engine) -> Tuple[Dict[str, np.ndarray],
                                          Optional[Dict[str, np.ndarray]],
                                          Optional[Dict[str, np.ndarray]],
                                          int]:
    """(fp32 params, exp_avg, exp_avg_sq, step) as dotted-name dicts."""
    if getattr(engine, "param_offload_enabled", False):
        co = engine._param_offload
        if co._partitioned:
            raise NotImplementedError(
                "universal export of a multi-process partitioned offload run: "
                "each process holds only its master shards — resume "
                "single-process from the partition checkpoint and export there")
        params = _dotted_tree(co.full_params_host())
        # flat moments follow the coordinator's global leaf order, which is also
        # the leaf order of full_params_host's flattening
        if co.nvme is not None:
            ms, vs = co.nvme.read_moments()
            step = int(co.step_count)
        elif co.kind in ("adam", "adamw"):
            sd = co.opt.state_dict()
            ms, vs, step = sd["m"], sd["v"], int(sd["step"])
        else:
            ms = vs = None
            step = int(co.step_count)
        m_named = v_named = None
        if ms is not None:
            names = list(params.keys())
            if not (len(names) == len(ms)):
                raise AssertionError('len(names) == len(ms)')
            m_named = {n: np.asarray(m, np.float32).reshape(params[n].shape)
                       for n, m in zip(names, ms)}
            v_named = {n: np.asarray(v, np.float32).reshape(params[n].shape)
                       for n, v in zip(names, vs)}
        return params, m_named, v_named, step

    import jax
    state = engine.state
    step = int(getattr(engine, "global_steps", 0))
    if getattr(engine, "offload_enabled", False):
        # ZeRO-Offload: the fp32 MASTERS live in the host tier (device params are
        # compute-dtype-rounded copies), and so do the Adam moments
        tier = engine._offload_tier
        if getattr(tier, "_partitioned", False):
            raise NotImplementedError(
                "universal export of a multi-process partitioned offload run: "
                "each process holds only its master shards — resume "
                "single-process from the partition checkpoint and export there")
        leaves, treedef = jax.tree_util.tree_flatten(state.params)
        shapes = [tuple(l.shape) for l in leaves]
        tree = jax.tree_util.tree_unflatten(
            treedef, [np.asarray(m, np.float32).reshape(s)
                      for m, s in zip(tier.masters, shapes)])
        params = _dotted_tree(tree)
        names = list(params.keys())        # == tree-flatten leaf order
        m_named = v_named = None
        if tier.nvme is not None:
            ms, vs = tier.nvme.read_moments()
        elif tier.kind == "adam":
            sd = tier.opt.state_dict()
            ms, vs = sd["m"], sd["v"]
        else:
            ms = vs = None
            logger.warning(
                f"universal export: optimizer kind {tier.kind!r} has no "
                "exp_avg/exp_avg_sq — the checkpoint carries weights only and a "
                "torch-side resume restarts optimizer state from zero")
        if ms is not None:
            if not (len(names) == len(ms)):
                raise AssertionError('len(names) == len(ms)')
            m_named = {n: np.asarray(m, np.float32).reshape(params[n].shape)
                       for n, m in zip(names, ms)}
            v_named = {n: np.asarray(v, np.float32).reshape(params[n].shape)
                       for n, v in zip(names, vs)}
        return params, m_named, v_named, step

    # _dotted_tree already makes the fp32 host copy per leaf — no outer tree_map
    # (that would transiently double host RAM on large models)
    params = _dotted_tree(state.params)
    m_named = v_named = None
    opt = state.opt_state
    if hasattr(opt, "exp_avg") and hasattr(opt, "exp_avg_sq"):
        # note: iteration stays engine.global_steps, NOT opt.step — fp16
        # overflow-skipped steps advance the former but not the latter, and a
        # torch-side resume schedules LR/data off the training iteration
        m_named = _dotted_tree(opt.exp_avg)
        v_named = _dotted_tree(opt.exp_avg_sq)
    else:
        logger.warning(
            "universal export: optimizer state has no exp_avg/exp_avg_sq — the "
            "checkpoint carries weights only and a torch-side resume restarts "
            "optimizer state from zero")
    return params, m_named, v_named, step


def _unflatten(named: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Dotted names back to a nested dict (for the mp_rank module payload)."""
    root: Dict[str, Any] = {}
    for name, arr in named.items():
        parts = name.split(".")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def _write_universal(params: Dict[str, np.ndarray],
                     m_named: Optional[Dict[str, np.ndarray]],
                     v_named: Optional[Dict[str, np.ndarray]],
                     step: int, save_dir: str, tag: str,
                     layer_files: Optional[Dict[str, Dict[str, np.ndarray]]]
                     = None) -> str:
    """Writer shared by all export entry points (see layout in
    :func:`export_universal_checkpoint`). ``layer_files`` adds reference
    pipeline-style per-layer files (``layer_XX-model_00-model_states.pt``)."""
    import torch

    path = os.path.join(save_dir, str(tag))
    zero_dir = os.path.join(path, "zero")
    os.makedirs(zero_dir, exist_ok=True)

    for name, arr in params.items():
        pdir = os.path.join(zero_dir, name)
        os.makedirs(pdir, exist_ok=True)
        torch.save({PARAM: torch.from_numpy(np.ascontiguousarray(arr)),
                    CAT_DIM: 0}, os.path.join(pdir, f"{FP32_NAME}.pt"))
        if m_named is not None and name in m_named:
            torch.save({PARAM: torch.from_numpy(
                np.ascontiguousarray(m_named[name])), CAT_DIM: 0},
                os.path.join(pdir, f"{EXP_AVG}.pt"))
            torch.save({PARAM: torch.from_numpy(
                np.ascontiguousarray(v_named[name])), CAT_DIM: 0},
                os.path.join(pdir, f"{EXP_AVG_SQ}.pt"))

    for fname, tensors in (layer_files or {}).items():
        torch.save({n: torch.from_numpy(np.ascontiguousarray(a))
                    for n, a in tensors.items()}, os.path.join(path, fname))

    module = _unflatten({n: torch.from_numpy(np.ascontiguousarray(a))
                         for n, a in params.items()})
    shapes = OrderedDict((n, tuple(a.shape)) for n, a in params.items())
    torch.save({"module": module, "param_shapes": shapes, "iteration": step,
                "dp_world_size": 1, "mp_world_size": 1},
               os.path.join(path, "mp_rank_00_model_states.pt"))
    with open(os.path.join(save_dir, "latest_universal"), "w") as f:
        f.write(str(tag))
    logger.info(f"universal checkpoint exported to {path} "
                f"({len(params)} params, step {step})")
    return path


def export_universal_checkpoint(engine, save_dir: str,
                                tag: str = "universal") -> str:
    """Write the engine's state as a reference universal checkpoint.

    Layout under ``save_dir/tag``::

        zero/<param_name>/fp32.pt         {"param": fp32 tensor, "cat_dim": 0}
        zero/<param_name>/exp_avg.pt      (when Adam moments exist)
        zero/<param_name>/exp_avg_sq.pt
        mp_rank_00_model_states.pt        module weights + param_shapes + iteration
        latest_universal                  tag pointer

    Pipeline engines additionally get reference per-layer files
    (``layer_XX-model_00-model_states.pt``, reference ``runtime/pipe/module.py:570``)
    with the stacked body un-stacked per pipeline position. Returns the path.
    """
    from ..runtime.pipe.engine import PipelineEngine

    if isinstance(engine, PipelineEngine):
        params, m_named, v_named, step, layer_files = \
            _gather_pipeline_state(engine)
        return _write_universal(params, m_named, v_named, step, save_dir, tag,
                                layer_files=layer_files)
    params, m_named, v_named, step = _gather_engine_state(engine)
    return _write_universal(params, m_named, v_named, step, save_dir, tag)


def _gather_pipeline_state(engine):
    """Un-stack a PipelineEngine's pre/body(stacked)/post/tied tree into per-layer
    dotted names (``<pos>.<param>``; the stacked body leaf ``body.x.y`` of shape
    ``(L, ...)`` becomes ``<pos_i>.x.y`` per body layer i — reference per-layer
    checkpoint naming, ``runtime/pipe/module.py:570``) plus per-layer files."""
    module = engine.pipeline_module
    params = engine.state.params
    step = int(getattr(engine, "global_steps", 0))
    opt = engine.state.opt_state
    has_moments = hasattr(opt, "exp_avg") and hasattr(opt, "exp_avg_sq")

    def unstack(seg_tree, take):
        """body leaves → per-layer dicts: {local_sub_name: arr[take]}"""
        return {n: a[take] for n, a in _dotted_tree(seg_tree).items()}

    out_p: Dict[str, np.ndarray] = {}
    out_m: Dict[str, np.ndarray] = {}
    out_v: Dict[str, np.ndarray] = {}
    layer_files: Dict[str, Dict[str, np.ndarray]] = {}

    tied_seen = set()
    for i in range(len(module._layers)):
        lk = f"{i:02d}"
        key = module._tied_keys[i]
        if key is not None:
            if key in tied_seen:
                continue            # tied reuse: saved at its first position
            tied_seen.add(key)
            named = _dotted_tree(params["tied"][key])
            sub_m = _dotted_tree(opt.exp_avg["tied"][key]) if has_moments \
                else None
            sub_v = _dotted_tree(opt.exp_avg_sq["tied"][key]) if has_moments \
                else None
        elif module.body_start <= i < module.body_end:
            bi = i - module.body_start
            named = unstack(params["body"], bi)
            sub_m = unstack(opt.exp_avg["body"], bi) if has_moments else None
            sub_v = unstack(opt.exp_avg_sq["body"], bi) if has_moments else None
        else:
            seg = "pre" if i < module.body_start else "post"
            if str(i) not in params[seg]:
                continue            # parameterless layer
            named = _dotted_tree(params[seg][str(i)])
            sub_m = (_dotted_tree(opt.exp_avg[seg][str(i)])
                     if has_moments else None)
            sub_v = (_dotted_tree(opt.exp_avg_sq[seg][str(i)])
                     if has_moments else None)
        layer_files[f"layer_{lk}-model_00-model_states.pt"] = named
        for n, a in named.items():
            out_p[f"{lk}.{n}"] = a
            if sub_m is not None:
                out_m[f"{lk}.{n}"] = sub_m[n]
                out_v[f"{lk}.{n}"] = sub_v[n]
    if not has_moments:
        logger.warning(
            "universal export: pipeline optimizer state has no exp_avg/"
            "exp_avg_sq — the checkpoint carries weights only")
    return (out_p, out_m or None, out_v or None, step, layer_files)


def consolidate_partitioned_checkpoint(ckpt_dir: str, tag: str, save_dir: str,
                                       out_tag: str = "universal") -> str:
    """OFFLINE consolidation of a multi-process partitioned offload run: read every
    rank's ``offload_state_part{r}.npz`` partition file, merge the owned master
    shards into full fp32 leaves, and write one universal checkpoint — the
    partitioned-tier analogue of ``zero_to_fp32`` (reference
    ``utils/zero_to_fp32.py:483`` consolidating per-rank zero shards).

    No engine or mesh needed: the partition files are self-describing
    (``ParamOffloadCoordinator._partition_meta``).
    """
    import glob
    import json

    prefix = os.path.join(ckpt_dir, str(tag), "offload_state")
    files = sorted(glob.glob(prefix + "_part*.npz"),
                   key=lambda f: int(f.rsplit("_part", 1)[1].split(".")[0]))
    if not files:
        raise FileNotFoundError(
            f"no partition files matching {prefix}_part*.npz — was this "
            "checkpoint written by a multi-process offload_param run?")

    full: Dict[str, np.ndarray] = {}
    m_full: Dict[str, np.ndarray] = {}
    v_full: Dict[str, np.ndarray] = {}
    step = 0
    meta0 = None
    seen_ranks: Dict[int, str] = {}
    for f in files:
        with np.load(f) as data:
            if "meta_json" not in data:
                raise ValueError(
                    f"{f} has no partition metadata (written by a pre-r5 "
                    "version) — re-save the checkpoint, or resume "
                    "single-process and export from the engine")
            meta = json.loads(bytes(data["meta_json"]).decode())
            meta0 = meta0 or meta
            if len(files) != meta["n_ranks"]:
                raise ValueError(
                    f"found {len(files)} partition files but the run had "
                    f"{meta['n_ranks']} ranks — a missing rank file would "
                    "leave its shards uninitialized in the consolidation")
            # rank-SET validation (not just a count): a duplicated rank file
            # (stale copy, botched rsync) passes the count check but leaves the
            # missing rank's np.empty slices as garbage in the merged leaves
            rank = int(meta.get("rank", -1))
            if rank in seen_ranks:
                raise ValueError(
                    f"duplicate rank {rank} partition files: "
                    f"{seen_ranks[rank]} and {f} both claim rank {rank} — the "
                    f"rank set must be exactly 0..{meta['n_ranks'] - 1}")
            seen_ranks[rank] = f
            if meta["nvme_params"]:
                raise NotImplementedError(
                    "consolidating an NVMe-partitioned run: masters live in the "
                    f"per-rank {prefix}_masters_p<r> directories, not the "
                    "partition files — resume on the writing topology and "
                    "export from the engine")
            step = max(step, int(data["step"]))
            has_moments = (meta["kind"] in ("adam", "adamw")
                           and not meta["nvme_moments"])
            for i, slot in enumerate(meta["slots"]):
                if not slot["owned"]:
                    continue
                name = meta["leaf_names"][slot["key"]][slot["li"]]
                lshape = tuple(meta["leaf_shapes"][slot["key"]][slot["li"]])
                sl = tuple(slice(a, b) for a, b in slot["slice"])
                sshape = tuple(b - a for a, b in slot["slice"])
                if name not in full:
                    full[name] = np.empty(lshape, np.float32)
                full[name][sl] = np.asarray(data[f"master_{i}"],
                                            np.float32).reshape(sshape)
                if has_moments:
                    if name not in m_full:
                        m_full[name] = np.empty(lshape, np.float32)
                        v_full[name] = np.empty(lshape, np.float32)
                    m_full[name][sl] = np.asarray(data[f"m_{i}"],
                                                  np.float32).reshape(sshape)
                    v_full[name][sl] = np.asarray(data[f"v_{i}"],
                                                  np.float32).reshape(sshape)

    expected_ranks = set(range(meta0["n_ranks"]))
    if set(seen_ranks) != expected_ranks:
        missing_ranks = sorted(expected_ranks - set(seen_ranks))
        raise ValueError(
            f"partition rank set {sorted(seen_ranks)} != expected "
            f"{sorted(expected_ranks)} (missing ranks {missing_ranks}) — "
            "consolidating would leave their master shards uninitialized")
    expected = {n for k, names in meta0["leaf_names"].items() for n in names}
    missing = expected - set(full)
    if missing:
        raise ValueError(
            f"partition files do not cover every leaf (missing {sorted(missing)[:4]}"
            f"...): expected {meta0['n_ranks']} ranks, found {len(files)} files")
    if meta0["kind"] not in ("adam", "adamw") or meta0["nvme_moments"]:
        logger.warning(
            "consolidation: optimizer moments unavailable offline for kind="
            f"{meta0['kind']!r} (nvme_moments={meta0['nvme_moments']}) — the "
            "universal checkpoint carries weights only")
        m_full = v_full = {}
    return _write_universal(full, m_full or None, v_full or None, step,
                            save_dir, out_tag)


def export_fp32_state_dict(engine, out_file: str) -> Dict[str, Any]:
    """Consolidated fp32 weights as one torch state dict file
    (``zero_to_fp32.py``'s ``pytorch_model.bin`` output format)."""
    import torch

    params, _, _, _ = _gather_engine_state(engine)
    sd = OrderedDict((n, torch.from_numpy(np.ascontiguousarray(a)))
                     for n, a in params.items())
    os.makedirs(os.path.dirname(os.path.abspath(out_file)), exist_ok=True)
    torch.save(sd, out_file)
    logger.info(f"fp32 state dict ({len(sd)} tensors) written to {out_file}")
    return sd
