"""Compression configuration.

Behavioural equivalent of reference ``deepspeed/compression/config.py`` (the
``get_*`` parser pile over ``constants.py`` keys) as pydantic models. Same JSON surface
under ``"compression_training"``: ``weight_quantization`` / ``activation_quantization`` /
``sparse_pruning`` / ``row_pruning`` / ``head_pruning`` / ``channel_pruning`` each with
``shared_parameters`` + ``different_groups``, plus ``layer_reduction``.
"""

from typing import Dict, List, Optional

from pydantic import Field

from ..config.config_utils import ConfigModel


class FP16MixedQuantize(ConfigModel):
    enabled: bool = False
    quantize_change_ratio: float = Field(0.001, ge=0)


class WeightQuantizeShared(ConfigModel):
    """Reference ``get_weight_quantization_shared_parameters`` keys."""
    enabled: bool = False
    quantizer_kernel: bool = False
    schedule_offset: int = Field(0, ge=0)
    quantize_groups: int = Field(1, ge=1)
    quantize_verbose: bool = False
    quantization_type: str = "symmetric"      # symmetric | asymmetric
    rounding: str = "nearest"                 # nearest | stochastic
    quantize_weight_in_forward: bool = False
    fp16_mixed_quantize: FP16MixedQuantize = Field(default_factory=FP16MixedQuantize)


class ActivationQuantizeShared(ConfigModel):
    enabled: bool = False
    quantization_type: str = "symmetric"
    range_calibration: str = "dynamic"        # dynamic | static
    schedule_offset: int = Field(1000, ge=0)


class PruningShared(ConfigModel):
    enabled: bool = False
    method: str = "l1"                        # l1 | topk
    schedule_offset: int = Field(1000, ge=0)


class QuantizeGroup(ConfigModel):
    """One ``different_groups`` entry: which params, start→target bits, anneal period."""
    start_bits: int = Field(8, ge=1)
    target_bits: int = Field(8, ge=1)
    quantization_period: int = Field(1, ge=1)
    modules: List[str] = Field(default_factory=lambda: ["*"])
    related_modules: Optional[List[str]] = None


class PruneGroup(ConfigModel):
    dense_ratio: float = Field(0.5, gt=0, le=1)
    modules: List[str] = Field(default_factory=lambda: ["*"])
    related_modules: Optional[List[str]] = None
    num_heads: Optional[int] = None           # head pruning only


class QuantizeSection(ConfigModel):
    shared_parameters: WeightQuantizeShared = Field(
        default_factory=WeightQuantizeShared)
    different_groups: Dict[str, QuantizeGroup] = Field(default_factory=dict)


class ActQuantizeSection(ConfigModel):
    shared_parameters: ActivationQuantizeShared = Field(
        default_factory=ActivationQuantizeShared)
    different_groups: Dict[str, QuantizeGroup] = Field(default_factory=dict)


class PruneSection(ConfigModel):
    shared_parameters: PruningShared = Field(default_factory=PruningShared)
    different_groups: Dict[str, PruneGroup] = Field(default_factory=dict)


class LayerReduction(ConfigModel):
    """Reference ``get_layer_reduction``: distill a deep teacher into a shallower
    student by keeping selected teacher layers."""
    enabled: bool = False
    keep_number_layer: Optional[int] = None
    module_name_prefix: str = ""
    teacher_layer: List[int] = Field(default_factory=list)
    other_module_name: List[str] = Field(default_factory=list)


def _normalize_groups(section: dict) -> dict:
    """Reference nests group params under ``"params"``; flatten to our model."""
    out = dict(section)
    groups = {}
    for name, g in section.get("different_groups", {}).items():
        flat = dict(g.get("params", {}))
        if "modules" in g:
            flat["modules"] = g["modules"]
        if "related_modules" in g:
            flat["related_modules"] = g["related_modules"]
        groups[name] = flat
    out["different_groups"] = groups
    return out


class CompressionConfig:
    """Parsed ``compression_training`` block."""

    def __init__(self, param_dict: Optional[dict] = None):
        pd = dict(param_dict or {})
        self.layer_reduction = LayerReduction(
            **({"enabled": True, **pd["layer_reduction"]}
               if isinstance(pd.get("layer_reduction"), dict) else {}))
        self.weight_quantization = QuantizeSection(
            **_normalize_groups(pd.get("weight_quantization", {})))
        self.activation_quantization = ActQuantizeSection(
            **_normalize_groups(pd.get("activation_quantization", {})))
        self.sparse_pruning = PruneSection(
            **_normalize_groups(pd.get("sparse_pruning", {})))
        self.row_pruning = PruneSection(
            **_normalize_groups(pd.get("row_pruning", {})))
        self.head_pruning = PruneSection(
            **_normalize_groups(pd.get("head_pruning", {})))
        self.channel_pruning = PruneSection(
            **_normalize_groups(pd.get("channel_pruning", {})))
        if self.weight_quantization.shared_parameters.enabled and \
                not self.weight_quantization.different_groups:
            raise ValueError("weight_quantization enabled requires different_groups")

    @property
    def any_enabled(self) -> bool:
        return (self.weight_quantization.shared_parameters.enabled or
                self.activation_quantization.shared_parameters.enabled or
                self.sparse_pruning.shared_parameters.enabled or
                self.row_pruning.shared_parameters.enabled or
                self.head_pruning.shared_parameters.enabled or
                self.channel_pruning.shared_parameters.enabled or
                self.layer_reduction.enabled)
