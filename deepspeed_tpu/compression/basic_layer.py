"""Compression primitives: QAT quantization + structured pruning masks.

Behavioural equivalent of reference ``deepspeed/compression/basic_layer.py`` (925 LoC:
``LinearLayer_Compress``, ``QuantAct``, ``Embedding_Compress``) re-designed functionally:
instead of nn.Module subclasses holding mutable masks, these are pure jit-safe transforms
on weight arrays. Quantize-dequantize uses a straight-through estimator
(``jax.custom_vjp`` identity backward — the ``SymQuantizer.apply``/autograd.Function role);
masks are plain multiplications, so masked weights get zero gradient exactly as the
reference's ``weight * mask`` forward does.

All transforms accept traced step-dependent arguments (e.g. annealed ``bits``), so the
compression schedule runs inside the compiled train step without recompilation.
"""

from typing import Optional

import jax
import jax.numpy as jnp


# --------------------------------------------------------------------- quantization
@jax.custom_vjp
def _ste(x, qx):
    """Forward: quantized value; backward: identity to x (straight-through)."""
    return qx


def _ste_fwd(x, qx):
    return qx, None


def _ste_bwd(_, g):
    return g, jnp.zeros_like(g)


_ste.defvjp(_ste_fwd, _ste_bwd)


def _grouped(x, groups: int):
    flat = x.reshape(-1)
    n = flat.shape[0]
    g = groups
    while n % g:
        g -= 1
    return flat.reshape(g, n // g), g


def quantize_dequantize(x, bits, quantization_type: str = "symmetric",
                        groups: int = 1, stochastic: bool = False,
                        rng: Optional[jax.Array] = None):
    """Fake-quantize ``x`` to ``bits`` (traced ok) per group; straight-through grads.

    symmetric: scale = max|x| / (2^(b-1)-1), zero-point-free (reference SymQuantizer);
    asymmetric: affine over [min, max] (reference AsymQuantizer).
    """
    orig_shape, orig_dtype = x.shape, x.dtype
    xg, _ = _grouped(x.astype(jnp.float32), groups)
    bits = jnp.asarray(bits, jnp.float32)
    if quantization_type == "symmetric":
        qmax = 2.0 ** (bits - 1.0) - 1.0
        amax = jnp.max(jnp.abs(xg), axis=1, keepdims=True)
        scale = jnp.where(amax > 0, amax / qmax, 1.0)
        q = xg / scale
        if stochastic:
            if not (rng is not None):
                raise AssertionError("stochastic rounding needs an rng")
            q = jnp.floor(q + jax.random.uniform(rng, q.shape))
        else:
            q = jnp.round(q)
        q = jnp.clip(q, -qmax, qmax) * scale
    elif quantization_type == "asymmetric":
        levels = 2.0 ** bits - 1.0
        lo = jnp.min(xg, axis=1, keepdims=True)
        hi = jnp.max(xg, axis=1, keepdims=True)
        scale = jnp.where(hi > lo, (hi - lo) / levels, 1.0)
        q = (xg - lo) / scale
        if stochastic:
            if not (rng is not None):
                raise AssertionError("stochastic rounding needs an rng")
            q = jnp.floor(q + jax.random.uniform(rng, q.shape))
        else:
            q = jnp.round(q)
        q = jnp.clip(q, 0.0, levels) * scale + lo
    else:
        raise ValueError(f"quantization_type {quantization_type!r} "
                         "(symmetric|asymmetric)")
    q = q.reshape(orig_shape).astype(orig_dtype)
    return _ste(x, q)


def quantize_activation(x, bits, quantization_type: str = "symmetric",
                        static_range: Optional[tuple] = None):
    """Activation fake-quant (reference ``QuantAct``): dynamic per-tensor range, or a
    calibrated static range."""
    if static_range is not None:
        lo, hi = static_range
        x = jnp.clip(x, lo, hi)
    return quantize_dequantize(x, bits, quantization_type, groups=1)


# --------------------------------------------------------------------- pruning masks
def sparse_mask(w, dense_ratio: float, method: str = "l1"):
    """Unstructured |w| top-k mask (reference ``enable_sparse_pruning`` l1/topk)."""
    flat = jnp.abs(w.reshape(-1))
    k = max(1, int(flat.shape[0] * dense_ratio))
    threshold = jax.lax.top_k(flat, k)[0][-1]
    return (jnp.abs(w) >= threshold).astype(w.dtype)


def row_mask(w, dense_ratio: float, method: str = "l1"):
    """Keep rows (output neurons, dim 0) with largest L1 norm (reference
    ``enable_row_pruning``)."""
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(1, w.ndim)))
    k = max(1, int(norms.shape[0] * dense_ratio))
    threshold = jax.lax.top_k(norms, k)[0][-1]
    keep = norms >= threshold
    return keep.astype(w.dtype).reshape((-1,) + (1,) * (w.ndim - 1))


def head_mask(w, dense_ratio: float, num_heads: int, method: str = "l1"):
    """Keep attention heads with largest L1 norm; ``w`` is the attention output
    projection (in_dim split into heads along dim 0 — reference
    ``enable_head_pruning`` on attn_ow)."""
    in_dim = w.shape[0]
    if not (in_dim % num_heads == 0):
        raise AssertionError((in_dim, num_heads))
    per_head = w.reshape(num_heads, in_dim // num_heads, *w.shape[1:])
    norms = jnp.sum(jnp.abs(per_head), axis=tuple(range(1, per_head.ndim)))
    k = max(1, int(num_heads * dense_ratio))
    threshold = jax.lax.top_k(norms, k)[0][-1]
    keep = (norms >= threshold).astype(w.dtype)
    return jnp.repeat(keep, in_dim // num_heads).reshape(
        (in_dim,) + (1,) * (w.ndim - 1))


def channel_mask(w, dense_ratio: float, method: str = "l1"):
    """Keep input channels (dim 1) with largest L1 norm (reference
    ``enable_channel_pruning`` for conv)."""
    axes = (0,) + tuple(range(2, w.ndim))
    norms = jnp.sum(jnp.abs(w), axis=axes)
    k = max(1, int(norms.shape[0] * dense_ratio))
    threshold = jax.lax.top_k(norms, k)[0][-1]
    keep = norms >= threshold
    return keep.astype(w.dtype).reshape((1, -1) + (1,) * (w.ndim - 2))
