"""Compression suite (reference ``deepspeed/compression``): QAT quantization,
structured pruning, layer reduction — functional, jit-safe transforms."""
from .basic_layer import (channel_mask, head_mask, quantize_activation,
                          quantize_dequantize, row_mask, sparse_mask)
from .compress import (init_compression, redundancy_clean, stacked_layer_reduction,
                       student_initialization)
from .config import CompressionConfig
from .scheduler import CompressionScheduler
