"""Compression entry points.

Behavioural equivalent of reference ``deepspeed/compression/compress.py``
(``init_compression:31``, ``redundancy_clean:103``, ``student_initialization:161``):

- :func:`init_compression` builds a :class:`CompressionScheduler` from a ds_config —
  the engine calls it automatically when ``compression_training`` is present and runs
  the scheduler's QAT transform inside the compiled step;
- :func:`redundancy_clean` bakes pruning masks into the weights permanently (the
  reference's ``fix_*_helper`` pass after training);
- :func:`student_initialization` implements layer_reduction: initialise a shallow
  student from chosen teacher layers.
"""

import re
from typing import Any, Dict, Optional, Union

import jax
import jax.numpy as jnp

from .config import CompressionConfig
from .scheduler import CompressionScheduler, _path_str


def init_compression(abstract_or_params: Any,
                     ds_config: Union[dict, "object"]) -> CompressionScheduler:
    """Reference ``init_compression:31``: returns the scheduler (the model is pure
    data here — no module surgery to do)."""
    if isinstance(ds_config, dict):
        cc = CompressionConfig(ds_config.get("compression_training", ds_config))
    elif isinstance(ds_config, CompressionConfig):
        cc = ds_config
    else:
        cc = CompressionConfig(getattr(ds_config, "compression_config", {}))
    return CompressionScheduler(cc, abstract_or_params)


def redundancy_clean(params: Any, ds_config: Union[dict, CompressionConfig]) -> Any:
    """Apply final masks destructively (reference ``redundancy_clean:103``); quantized
    groups are fake-quantized at target bits so the saved weights equal serving-time
    values."""
    scheduler = init_compression(params, ds_config)
    import numpy as np
    final_step = jnp.int32(2 ** 30)  # all schedule offsets passed, bits at target
    return scheduler.qat(params, final_step)


def student_initialization(student_params: Any, teacher_params: Any,
                           ds_config: Union[dict, CompressionConfig]) -> Any:
    """Layer reduction (reference ``student_initialization:161``): copy
    ``teacher_layer[i]`` of the teacher into layer ``i`` of the student for params
    matching ``module_name_prefix.<index>.``; ``other_module_name`` params copy as-is.
    """
    if isinstance(ds_config, CompressionConfig):
        cc = ds_config
    else:
        cc = CompressionConfig(ds_config.get("compression_training", ds_config))
    lr = cc.layer_reduction
    if not (lr.enabled):
        raise AssertionError("layer_reduction not enabled")
    teacher_flat = {_path_str(p): l for p, l in
                    jax.tree_util.tree_flatten_with_path(teacher_params)[0]}
    prefix = lr.module_name_prefix

    def remap(path_str: str):
        """student path -> teacher path (student layer i reads teacher_layer[i])."""
        if prefix and path_str.startswith(prefix):
            rest = path_str[len(prefix):].lstrip(".")
            m = re.match(r"(\d+)(.*)", rest)
            if m:
                idx = int(m.group(1))
                if idx < len(lr.teacher_layer):
                    t_idx = lr.teacher_layer[idx]
                    return f"{prefix}.{t_idx}{m.group(2)}" \
                        if not prefix.endswith(".") else \
                        f"{prefix}{t_idx}{m.group(2)}"
        return path_str

    flat, treedef = jax.tree_util.tree_flatten_with_path(student_params)
    out = []
    for path, leaf in flat:
        pstr = _path_str(path)
        src = remap(pstr)
        t = teacher_flat.get(src)
        if t is not None and tuple(t.shape) == tuple(leaf.shape):
            out.append(jnp.asarray(t, leaf.dtype))
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def stacked_layer_reduction(teacher_stack: Any, teacher_layers) -> Any:
    """TPU-native convenience for our stacked-body models (params["body"] leaves with a
    leading layer dim): student body = teacher body gathered at ``teacher_layers``."""
    idx = jnp.asarray(list(teacher_layers), jnp.int32)
    return jax.tree_util.tree_map(lambda l: l[idx], teacher_stack)
