"""Compression scheduler: which transform applies to which parameter at which step.

Behavioural equivalent of reference ``deepspeed/compression/scheduler.py``
(``compression_scheduler``): matches config group ``modules`` patterns against parameter
paths, gates each method on its ``schedule_offset``, and anneals quantization bits from
``start_bits`` to ``target_bits`` (halving every ``quantization_period`` steps, the
reference's QAT bit schedule).

TPU-native difference: instead of flipping booleans on nn.Modules each step, the
scheduler builds ONE jit-safe transform over the param pytree; step-dependent gating uses
``jnp.where`` on the traced global step so the compiled train step never recompiles.
"""

import fnmatch
import re
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist
from .basic_layer import (channel_mask, head_mask, quantize_dequantize, row_mask,
                          sparse_mask)
from .config import CompressionConfig


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return ".".join(parts)


def _matches(path: str, patterns: List[str]) -> bool:
    for pat in patterns:
        if pat == "*" or fnmatch.fnmatch(path, pat) or fnmatch.fnmatch(path, pat + "*"):
            return True
        try:  # reference module_scope entries may be regexes; glob syntax isn't
            if re.search(pat, path):
                return True
        except re.error:
            pass
    return False


class CompressionScheduler:
    """Build per-leaf compression plans from the config; apply them QAT-style."""

    def __init__(self, config: CompressionConfig, abstract_params: Any):
        self.config = config
        # leaf path -> list of (kind, group) plans, resolved once against the tree
        self.plans: Dict[str, List[Tuple[str, Any]]] = {}
        flat, _ = jax.tree_util.tree_flatten_with_path(abstract_params)
        sections = [
            ("weight_quantization", config.weight_quantization),
            ("sparse_pruning", config.sparse_pruning),
            ("row_pruning", config.row_pruning),
            ("head_pruning", config.head_pruning),
            ("channel_pruning", config.channel_pruning),
        ]
        # weight-matrix rank per pruning kind; +1 permitted for stacked bodies
        # (leading layer dim), handled by vmap at apply time
        base_ndim = {"sparse_pruning": 2, "row_pruning": 2, "head_pruning": 2,
                     "channel_pruning": 4}
        for path, leaf in flat:
            pstr = _path_str(path)
            last = pstr.rsplit(".", 1)[-1].lower()
            # biases/norm params are never compressed (reference targets Linear
            # weights); name check matters because stacked-body models carry a
            # leading layer dim that makes biases 2-D
            if getattr(leaf, "ndim", 0) < 2 or last in (
                    "bias", "b", "scale", "ln_1", "ln_2", "ln_f", "embedding"):
                continue
            for kind, section in sections:
                if not section.shared_parameters.enabled:
                    continue
                if kind in base_ndim and \
                        section.shared_parameters.method not in ("l1",):
                    raise NotImplementedError(
                        f"{kind} method {section.shared_parameters.method!r}: only "
                        "'l1' (magnitude) is implemented; the reference's learnable "
                        "'topk' scores are not")
                if kind in base_ndim and \
                        leaf.ndim not in (base_ndim[kind], base_ndim[kind] + 1):
                    log_dist(f"compression: skipping {kind} for {pstr} "
                             f"(ndim {leaf.ndim} unsupported)", ranks=[0])
                    continue
                for group in section.different_groups.values():
                    if _matches(pstr, group.modules):
                        self.plans.setdefault(pstr, []).append((kind, group))
                        break
        if self.plans:
            log_dist(f"compression: {len(self.plans)} parameters matched "
                     f"({sorted(self.plans)[:4]}...)", ranks=[0])

    @property
    def active(self) -> bool:
        return bool(self.plans)

    # ------------------------------------------------------------------ bits anneal
    @staticmethod
    def _annealed_bits(step, start_bits: int, target_bits: int, period: int,
                       offset: int):
        """start → target, halving every ``period`` steps AFTER quantization activates
        at ``offset`` (traced-step safe)."""
        if start_bits == target_bits:
            return jnp.float32(start_bits)
        active_steps = jnp.maximum(step - offset, 0).astype(jnp.float32)
        halvings = jnp.floor(active_steps / period)
        bits = jnp.float32(start_bits) * (0.5 ** halvings)
        return jnp.maximum(bits, jnp.float32(target_bits))

    # ------------------------------------------------------------------ pruning
    @staticmethod
    def _prune_mask(kind: str, w, group, sp):
        """Mask for one leaf; stacked-body leaves (one extra leading layer dim) get
        the per-layer mask vmapped over that dim."""
        base_ndim = 4 if kind == "channel_pruning" else 2
        if kind == "sparse_pruning":
            fn = lambda x: sparse_mask(x, group.dense_ratio, sp.method)
        elif kind == "row_pruning":
            fn = lambda x: row_mask(x, group.dense_ratio, sp.method)
        elif kind == "head_pruning":
            if not (group.num_heads):
                raise AssertionError("head_pruning groups need num_heads")
            fn = lambda x: head_mask(x, group.dense_ratio, group.num_heads,
                                     sp.method)
        else:
            fn = lambda x: channel_mask(x, group.dense_ratio, sp.method)
        if w.ndim == base_ndim + 1:
            return jax.vmap(fn)(w)
        return fn(w)

    # ------------------------------------------------------------------ apply
    def qat(self, params: Any, step) -> Any:
        """Apply active compression to matched leaves inside the train step.

        ``step`` is the traced global step; each transform is gated by
        ``step >= schedule_offset`` via where-select so enabling is a data change,
        not a recompile.
        """
        step = jnp.asarray(step, jnp.int32)

        def one(path, leaf):
            pstr = _path_str(path)
            plans = self.plans.get(pstr)
            if not plans:
                return leaf
            out = leaf
            for kind, group in plans:
                if kind == "weight_quantization":
                    sp = self.config.weight_quantization.shared_parameters
                    bits = self._annealed_bits(step, group.start_bits,
                                               group.target_bits,
                                               group.quantization_period,
                                               sp.schedule_offset)
                    stochastic = sp.rounding == "stochastic"
                    # crc32, not hash(): reproducible across processes/resumes
                    rng = (jax.random.fold_in(
                        jax.random.fold_in(jax.random.PRNGKey(0x51A7), step),
                        zlib.crc32(pstr.encode()) & 0x7FFFFFFF)
                        if stochastic else None)
                    q = quantize_dequantize(out, bits, sp.quantization_type,
                                            groups=sp.quantize_groups,
                                            stochastic=stochastic, rng=rng)
                    out = jnp.where(step >= sp.schedule_offset, q, out)
                else:
                    section = getattr(self.config, kind)
                    sp = section.shared_parameters
                    mask = self._prune_mask(kind, out, group, sp)
                    out = jnp.where(step >= sp.schedule_offset, out * mask, out)
            return out

        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        return jax.tree_util.tree_unflatten(
            treedef, [one(p, l) for p, l in flat])

    def masks(self, params: Any) -> Dict[str, Any]:
        """Final pruning masks per matched leaf (for ``redundancy_clean``)."""
        out = {}
        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        for path, leaf in flat:
            pstr = _path_str(path)
            for kind, group in self.plans.get(pstr, []):
                if kind != "weight_quantization":
                    sp = getattr(self.config, kind).shared_parameters
                    out[pstr] = self._prune_mask(kind, leaf, group, sp)
        return out
