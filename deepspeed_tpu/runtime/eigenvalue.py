"""Per-block Hessian max-eigenvalue estimation (power iteration).

Behavioural equivalent of reference ``deepspeed/runtime/eigenvalue.py``
(``Eigenvalue:9``, ``compute_eigenvalue:63``): estimate the dominant curvature of each
transformer block to schedule mixed quantization (MoQ) — blocks with larger eigenvalues
quantize later/slower.

TPU-native realisation: the reference double-backwards through stored autograd graphs;
here the Hessian-vector product is ``jax.jvp`` of ``jax.grad`` (forward-over-reverse),
jitted once and reused across power iterations and blocks. Blocks are slices of a
STACKED parameter subtree (our models stack homogeneous layers on a leading dim), so a
block tangent is the full-tree tangent with zeros outside slice ``i``.
"""

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist


class Eigenvalue:
    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        if not (layer_name and layer_num > 0):
            raise AssertionError("eigenvalue requires layer_name (stacked subtree path) and layer_num")
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    # ------------------------------------------------------------------ helpers
    def _subtree(self, params):
        node = params
        for part in self.layer_name.split("."):
            node = node[part]
        return node

    @staticmethod
    def _normalize(tree, stability):
        sq = sum(jnp.sum(jnp.square(l)) for l in jax.tree_util.tree_leaves(tree))
        norm = jnp.sqrt(sq) + stability
        return jax.tree_util.tree_map(
            lambda l: jnp.nan_to_num(l / norm, posinf=0.0, neginf=0.0), tree)

    # ------------------------------------------------------------------ main
    def compute_eigenvalue(self, loss_fn: Callable[[Any], jnp.ndarray],
                           params: Any, scale: float = 1.0,
                           seed: int = 0) -> List[float]:
        """Dominant |eigenvalue| of the loss Hessian restricted to each block.

        ``loss_fn(params) -> scalar`` closes over the batch; returns the reference's
        post-processed values (normalised to [0, 1], invalid blocks → 1.0).
        """
        grad_fn = jax.grad(loss_fn)

        @jax.jit
        def hvp_block(p, v_block, block_idx):
            """HVP with a tangent living on slice ``block_idx`` of the stacked
            subtree; result restricted to that slice."""
            def embed(vb):
                tangent = jax.tree_util.tree_map(jnp.zeros_like, p)
                sub = self._subtree(tangent)
                sub_new = jax.tree_util.tree_map(
                    lambda z, s: z.at[block_idx].set(s), sub, vb)
                return _replace_subtree(tangent, self.layer_name, sub_new)

            _, hv = jax.jvp(grad_fn, (p,), (embed(v_block),))
            return jax.tree_util.tree_map(
                lambda l: jnp.nan_to_num(l[block_idx]), self._subtree(hv))

        sub = self._subtree(params)
        raw: List[float] = []
        for block in range(self.layer_num):
            rng = jax.random.PRNGKey(seed + block)
            leaves, treedef = jax.tree_util.tree_flatten(
                jax.tree_util.tree_map(lambda l: l[block], sub))
            keys = jax.random.split(rng, len(leaves))
            v = jax.tree_util.tree_unflatten(
                treedef, [jax.random.normal(k, l.shape, jnp.float32)
                          for k, l in zip(keys, leaves)])
            v = self._normalize(v, self.stability)

            current, previous = 1.0, 0.0
            for i in range(self.max_iter):
                if abs(current) == 0 or \
                        abs((current - previous) / current) < self.tol and i > 0:
                    break
                previous = current
                hv = hvp_block(params, v, block)
                current = float(sum(
                    jnp.sum(a * b) for a, b in zip(
                        jax.tree_util.tree_leaves(hv),
                        jax.tree_util.tree_leaves(v))))
                v = self._normalize(hv, self.stability)
                v = jax.tree_util.tree_map(lambda l: l / scale, v)
            raw.append(current * scale)
            if self.verbose:
                log_dist(f"block {block}: eigenvalue {raw[-1]:.4e}", ranks=[0])
        return self.post_process(raw)

    @staticmethod
    def post_process(values: List[float]) -> List[float]:
        """Reference ``post_process:152``: |v| / max|v|; invalid (0) blocks → 1.0."""
        max_value = abs(max(values, key=abs)) if values else 1.0
        if max_value == 0:
            return [1.0] * len(values)
        return [abs(v) / max_value if v != 0.0 else 1.0 for v in values]


def _replace_subtree(tree, dotted: str, new_subtree):
    parts = dotted.split(".")

    def rec(node, i):
        if i == len(parts):
            return new_subtree
        out = dict(node)
        out[parts[i]] = rec(node[parts[i]], i + 1)
        return out

    return rec(tree, 0)
