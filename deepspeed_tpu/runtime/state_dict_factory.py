"""Sharded state-dict loading.

Behavioural equivalent of reference ``deepspeed/runtime/state_dict_factory.py``
(``SDLoaderFactory:20``, ``MegatronSDLoader:214``, merge/split by MP degree) +
``module_inject/load_checkpoint.py``: big checkpoints arrive as MANY files (HF
``pytorch_model-0000x-of-0000N.bin`` / ``model-*.safetensors`` with an index json, or a
Megatron ``mp_rank_XX`` list); loading must stream shard-by-shard, never materialising
the full model on host — the reference's AutoTP/sharded-load requirement and the
round-1 VERDICT's "7B BLOOM needs sharded/streamed loading" item.

Design: a :class:`ShardedStateDict` is a lazy mapping name → tensor backed by the shard
index; tensors load on first access, and ``release_shard`` drops whole files once their
tensors are consumed. ``merge``/``split`` helpers re-partition query/key/value or
row/column-parallel weights across MP degrees (the MegatronSDLoader merge_state_dict /
split_state_dict capability) as pure numpy ops.
"""

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.logging import logger


class ShardedStateDict:
    """Lazy name → numpy tensor view over a sharded checkpoint directory.

    Supports: HF torch shards with ``pytorch_model.bin.index.json``, HF safetensors
    shards with ``model.safetensors.index.json``, single-file ``pytorch_model.bin`` /
    ``model.safetensors``.
    """

    def __init__(self, path: str):
        self.path = path
        self.weight_map: Dict[str, str] = {}
        self._cache: Dict[str, Dict[str, Any]] = {}   # shard file -> loaded dict
        self._format: Optional[str] = None
        self._resolve(path)

    # ------------------------------------------------------------------ resolve
    def _resolve(self, path: str):
        candidates = [
            ("pytorch_model.bin.index.json", "torch"),
            ("model.safetensors.index.json", "safetensors"),
        ]
        for idx_name, fmt in candidates:
            idx_path = os.path.join(path, idx_name)
            if os.path.isfile(idx_path):
                with open(idx_path) as f:
                    index = json.load(f)
                self.weight_map = dict(index["weight_map"])
                self._format = fmt
                logger.info(f"[state_dict] sharded {fmt} checkpoint: "
                            f"{len(set(self.weight_map.values()))} shards, "
                            f"{len(self.weight_map)} tensors")
                return
        for fname, fmt in (("pytorch_model.bin", "torch"),
                           ("model.safetensors", "safetensors")):
            fpath = os.path.join(path, fname)
            if os.path.isfile(fpath):
                self._format = fmt
                sd = self._load_shard(fname)
                self.weight_map = {k: fname for k in sd}
                return
        raise FileNotFoundError(
            f"No checkpoint found under {path} (looked for sharded index jsons, "
            "pytorch_model.bin, model.safetensors)")

    # ------------------------------------------------------------------ loading
    def _load_shard(self, fname: str) -> Dict[str, Any]:
        if fname not in self._cache:
            fpath = os.path.join(self.path, fname)
            if self._format == "torch":
                import torch
                self._cache[fname] = torch.load(fpath, map_location="cpu",
                                                weights_only=True)
            else:
                from safetensors.numpy import load_file
                self._cache[fname] = load_file(fpath)
        return self._cache[fname]

    def keys(self) -> List[str]:
        return list(self.weight_map)

    def __contains__(self, name: str) -> bool:
        return name in self.weight_map

    def __getitem__(self, name: str) -> np.ndarray:
        shard = self._load_shard(self.weight_map[name])
        t = shard[name]
        if hasattr(t, "detach"):   # torch tensor
            t = t.detach().to("cpu").float().numpy() if t.dtype.is_floating_point \
                else t.detach().cpu().numpy()
        return np.asarray(t)

    def get(self, name: str, default=None):
        return self[name] if name in self else default

    def release_shard(self, fname: str):
        """Free a consumed shard's host memory (streaming discipline)."""
        self._cache.pop(fname, None)

    def shards(self) -> List[str]:
        return sorted(set(self.weight_map.values()))

    def tensors_in_shard(self, fname: str) -> List[str]:
        return [k for k, v in self.weight_map.items() if v == fname]

    def stream(self):
        """Yield ``(name, tensor)`` shard-by-shard, releasing each shard after its
        tensors are consumed — peak host memory is one shard, not the model."""
        for fname in self.shards():
            for name in self.tensors_in_shard(fname):
                yield name, self[name]
            self.release_shard(fname)


# ---------------------------------------------------------------------- MP re-partition
def merge_mp_tensors(tensors: List[np.ndarray], axis: int) -> np.ndarray:
    """Merge model-parallel partitions back into one tensor
    (reference ``MegatronSDLoader.merge_state_dict``)."""
    return np.concatenate([np.asarray(t) for t in tensors], axis=axis)


def split_mp_tensor(tensor: np.ndarray, mp_degree: int, axis: int) -> List[np.ndarray]:
    """Split one tensor into MP partitions
    (reference ``MegatronSDLoader.split_state_dict``)."""
    if not (tensor.shape[axis] % mp_degree == 0):
        raise AssertionError((tensor.shape, mp_degree, axis))
    return list(np.split(np.asarray(tensor), mp_degree, axis=axis))


def merge_qkv_tensors(tensors: List[np.ndarray], axis: int = 0) -> np.ndarray:
    """Merge per-rank fused QKV partitions preserving the q/k/v interleaving
    (reference ``merge_query_key_value:239``): each rank holds [q_i; k_i; v_i] along
    ``axis``; the merged tensor is [q_0..q_n; k_0..k_n; v_0..v_n]."""
    parts = [np.split(np.asarray(t), 3, axis=axis) for t in tensors]
    merged = [np.concatenate([p[j] for p in parts], axis=axis) for j in range(3)]
    return np.concatenate(merged, axis=axis)


def split_qkv_tensor(tensor: np.ndarray, mp_degree: int, axis: int = 0) \
        -> List[np.ndarray]:
    """Inverse of :func:`merge_qkv_tensors` (reference ``split_query_key_value:270``)."""
    q, k, v = np.split(np.asarray(tensor), 3, axis=axis)
    qs = np.split(q, mp_degree, axis=axis)
    ks = np.split(k, mp_degree, axis=axis)
    vs = np.split(v, mp_degree, axis=axis)
    return [np.concatenate([qs[i], ks[i], vs[i]], axis=axis)
            for i in range(mp_degree)]


class SDLoaderFactory:
    """Reference ``SDLoaderFactory:20``: resolve a checkpoint descriptor to a loader."""

    @staticmethod
    def get_sd_loader_json(json_or_dir: str) -> "ShardedStateDict":
        if os.path.isdir(json_or_dir):
            return ShardedStateDict(json_or_dir)
        raise NotImplementedError(
            "Megatron-style descriptor jsons ({'type':..., 'checkpoints': [...]}) "
            "are not supported yet — point at the checkpoint DIRECTORY (HF index "
            "json / single-file layouts); use merge_mp_tensors/split_mp_tensor for "
            "MP re-partitioning")
