"""Runtime helpers: norms, clipping, memory reporting.

Behavioural equivalents of reference ``deepspeed/runtime/utils.py`` (1019 LoC):
``clip_grad_norm_``, ``get_global_norm``, ``CheckOverflow``, ``see_memory_usage``,
``DummyOptim``. The tensor math is pytree-functional and jit-safe; partitioned-flat-buffer
helpers have no TPU analogue (XLA owns layout) and are intentionally absent.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..utils.logging import logger


def global_norm(tree: Any) -> jnp.ndarray:
    """L2 norm over every leaf (fp32 accumulation). Reference ``get_global_norm``."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    sq = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(sq)


def clip_by_global_norm(tree: Any, max_norm: float,
                        norm: Optional[jnp.ndarray] = None) -> Any:
    """Reference ``clip_grad_norm_`` semantics (scale all grads by max_norm/total_norm)."""
    if norm is None:
        norm = global_norm(tree)
    # match torch semantics: clip only when norm exceeds max_norm
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda l: (l * factor).astype(l.dtype), tree)


def has_overflow(tree: Any) -> jnp.ndarray:
    """Any non-finite leaf? Reference ``CheckOverflow`` (runtime/utils.py)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.array(False)
    finite = jnp.array(True)
    for l in leaves:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(l.astype(jnp.float32))))
    return jnp.logical_not(finite)


def tree_cast(tree: Any, dtype) -> Any:
    """Cast floating leaves to ``dtype`` (dtype policy for mixed precision)."""
    def cast(l):
        if jnp.issubdtype(l.dtype, jnp.floating):
            return l.astype(dtype)
        return l
    return jax.tree_util.tree_map(cast, tree)


def tree_zeros_like(tree: Any, dtype=None) -> Any:
    return jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, dtype or l.dtype), tree)


def count_parameters(tree: Any) -> int:
    return sum(int(l.size) for l in jax.tree_util.tree_leaves(tree))


def see_memory_usage(message: str, force: bool = False):
    """Reference ``see_memory_usage``: device + host memory snapshot."""
    if not force:
        return
    try:
        dev = jax.local_devices()[0]
        stats = dev.memory_stats() or {}
        gb = 1024**3
        logger.info(
            f"{message} | device mem: in_use={stats.get('bytes_in_use', 0)/gb:.2f}GB "
            f"peak={stats.get('peak_bytes_in_use', 0)/gb:.2f}GB "
            f"limit={stats.get('bytes_limit', 0)/gb:.2f}GB")
    except Exception:
        logger.info(f"{message} | device memory stats unavailable")
    try:
        import resource
        rss_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / (1024**2)
        logger.info(f"{message} | host max RSS: {rss_gb:.2f}GB")
    except Exception:
        pass


class DummyOptim:
    """Placeholder optimizer when the user manages updates externally.

    Reference ``runtime/utils.py:DummyOptim``.
    """

    def __init__(self, params=None):
        self.params = params
