"""ZeRO-Offload: host-resident optimizer tier.

TPU-native re-design of the reference's CPU-offload machinery
(``runtime/zero/stage_1_and_2.py:130`` ``cpu_offload``, ``csrc/adam/cpu_adam.cpp``,
``runtime/swap_tensor/optimizer_utils.py:118``). The reference moves the fp32 optimizer
partition to pinned CPU memory and runs an AVX Adam there; we do the same with the whole-model
view natural to a single-controller JAX program:

- HBM holds ONLY compute-dtype (bf16/fp16) parameters and the in-flight gradient
  accumulator — the fp32 masters and both Adam moments live in host RAM as numpy buffers.
  Per-parameter HBM cost drops from 16 bytes (fp32 master + m + v + grad) to ~4, which is
  the reference's "13B on one V100" recipe re-based onto one TPU chip.
- The jitted train step ends at clipped, unscaled grads (cast to the transfer dtype);
  leaves D2H-stream with ``copy_to_host_async`` so transfers overlap each other.
- The native SIMD Adam (``ops/adam/cpu_adam.py``) updates masters in place; updated params
  are pushed back H2D already cast to compute dtype, placed per the engine's param
  shardings (``jax.device_put`` is async — the push overlaps the next batch's host work).

Multi-host: with ``jax.process_count() > 1`` the tier switches to PER-PROCESS PARTITIONS
(reference ``stage_1_and_2.py:130`` — cpu_offload is per-rank by construction): each
process's masters hold only the unique gradient shards addressable from its local
devices; the host optimizer updates that partition; the push reassembles a
gradient-sharded device array from the local slices and reshards it to the parameter
sharding inside one jitted identity — XLA emits the all-gather over ICI, the analogue of
the reference's post-step ``all_gather_dp_groups`` (``runtime/utils.py``).
"""

import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ...ops.adam.cpu_adam import DeepSpeedCPUAdam, adagrad_step, fp32_to_bf16, native_available
from ...utils.logging import log_dist


def cast_master_to(flat: np.ndarray, shape, compute_dtype) -> np.ndarray:
    """fp32 host master (flat) → compute-dtype host array, shaped for the push.
    Shared by both offload tiers so their numerics cannot diverge."""
    if compute_dtype == jax.numpy.bfloat16:
        return fp32_to_bf16(flat.reshape(shape))
    return flat.reshape(shape).astype(np.dtype(compute_dtype))


def _norm_index(index, shape) -> Tuple[Tuple[int, int], ...]:
    """Normalise a Shard.index (tuple of slices) to hashable ((start, stop), ...)."""
    return tuple((s.start or 0, s.stop if s.stop is not None else dim)
                 for s, dim in zip(index, shape))


def unique_local_shards(arr) -> List[Tuple[Tuple[Tuple[int, int], ...], np.ndarray]]:
    """Deduplicated (index, data) pairs for this process's addressable shards.

    Replicated leaves yield one full-size entry; sharded leaves yield each distinct
    local partition once (a device group replicating a shard contributes it once)."""
    out: Dict[Tuple, np.ndarray] = {}
    for shard in arr.addressable_shards:
        key = _norm_index(shard.index, arr.shape)
        if key not in out:
            out[key] = np.asarray(shard.data)
    return sorted(out.items())


def make_swap_handle(path: str, aio_config: dict, feature: str):
    """Shared NVMe-store setup: availability guard, swap dir, and an O_DIRECT-by-
    default aio handle (page-cache bypass — these tiers exist because the working
    set exceeds RAM; per-filesystem buffered fallback inside the handle). One place
    so the moment and parameter stores cannot diverge on aio-config handling."""
    import os
    from ...ops.aio.aio_handle import AsyncIOHandle, aio_available
    if not aio_available():
        raise RuntimeError(f"{feature} requires the native aio op (C++ toolchain)")
    os.makedirs(path, exist_ok=True)
    return AsyncIOHandle(
        thread_count=aio_config.get("thread_count", 1),
        block_size=aio_config.get("block_size", 1 << 20),
        queue_depth=aio_config.get("queue_depth", 8),
        o_direct=aio_config.get("use_o_direct", True))


class _NVMeMomentStore:
    """Adam moments on disk, double-buffered through the native aio handle.

    Layout: one file per leaf under ``path`` holding m then v back-to-back (fp32).
    ``adam_step_all`` pipelines: while leaf ``i`` runs the SIMD Adam on scratch buffer
    ``i % 2``, leaf ``i+1``'s moments stream into buffer ``(i + 1) % 2``.
    """

    def __init__(self, path: str, masters, aio_config: dict):
        import os
        from ...ops.aio.aio_handle import aligned_array, padded_len
        self.path = path
        self.handle = make_swap_handle(path, aio_config,
                                       "offload_optimizer.device=nvme")
        self._padded_len = padded_len
        # masters: numpy leaves or plain element counts
        self.sizes = [int(getattr(m, "size", m)) for m in masters]
        self._files = [os.path.join(path, f"moments_leaf{i}.bin")
                       for i in range(len(masters))]
        max_size = max(self.sizes)
        # 4096-aligned scratch with capacity padded to the O_DIRECT granularity
        cap = padded_len(2 * max_size, 4)
        self._scratch = [aligned_array(cap * 4, np.float32) for _ in range(2)]
        # lazy zero-init: a leaf whose file was never written reads as zeros from
        # the scratch fill — avoids a full-disk zero pass at startup that a
        # checkpoint resume would immediately overwrite anyway
        self._dirty = [False] * len(self.sizes)

    def _io_len(self, i: int) -> int:
        """Element count for leaf ``i``'s file IO (byte length 4096-padded for
        O_DIRECT; the pad tail is scratch garbage both ways, never consumed)."""
        return self._padded_len(2 * self.sizes[i], 4)

    def _fetch(self, i: int, buf: np.ndarray):
        """Start streaming leaf ``i``'s moments into ``buf`` (zeros if unwritten)."""
        if self._dirty[i]:
            self.handle.async_pread(buf[:self._io_len(i)], self._files[i])
        else:
            buf[:2 * self.sizes[i]] = 0.0

    def adam_step_all(self, masters, grads, lr, step, betas, eps, weight_decay,
                      adam_w_mode, bias_correction):
        from ...ops.adam.cpu_adam import adam_step
        n = len(masters)
        buf = self._scratch
        self._fetch(0, buf[0])
        self.handle.wait()
        for i in range(n):
            if i + 1 < n:  # overlap: next leaf's moments stream in during compute
                self._fetch(i + 1, buf[(i + 1) % 2])
            s = self.sizes[i]
            mv = buf[i % 2]
            adam_step(masters[i], mv[:s], mv[s:2 * s], grads[i], lr,
                      betas[0], betas[1], eps, weight_decay, adam_w_mode, step,
                      bias_correction)
            self.handle.async_pwrite(mv[:self._io_len(i)], self._files[i])
            self._dirty[i] = True
            self.handle.wait()

    # ---------------------------------------------------------- per-leaf streaming
    # (the combined masters+grads+moments update loop of the NVMe param tier
    # interleaves leaves across stores, so it drives this store leaf-by-leaf)
    def fetch_slot(self, i: int, slot: int):
        """Async-read leaf ``i``'s moments into double-buffer ``slot``."""
        self._fetch(i, self._scratch[slot])

    def slot_views(self, i: int, slot: int):
        """(m, v) fp32 views of leaf ``i`` inside double-buffer ``slot``."""
        s = self.sizes[i]
        mv = self._scratch[slot]
        return mv[:s], mv[s:2 * s]

    def write_slot(self, i: int, slot: int):
        """Async-write leaf ``i``'s moments back from double-buffer ``slot``."""
        self.handle.async_pwrite(self._scratch[slot][:self._io_len(i)],
                                 self._files[i])
        self._dirty[i] = True

    def wait(self):
        self.handle.wait()

    # ------------------------------------------------------------------ streaming ckpt
    def copy_files_to(self, dest_dir: str):
        """Checkpoint the on-disk moments by FILE COPY — no host-RAM materialisation
        (the moments are already serialized; reading them back only to re-serialize
        would blow the tier's memory budget)."""
        import os
        import shutil
        os.makedirs(dest_dir, exist_ok=True)
        self.handle.wait()
        for i, f in enumerate(self._files):
            if self._dirty[i]:
                shutil.copy2(f, os.path.join(dest_dir, os.path.basename(f)))

    def copy_files_from(self, src_dir: str):
        import os
        import shutil
        for i, f in enumerate(self._files):
            src = os.path.join(src_dir, os.path.basename(f))
            if os.path.isfile(src):
                # size gate BEFORE installing: the only accepted sizes are the
                # padded IO length and the EXACT pre-O_DIRECT legacy length
                # (2·s·4 bytes, padded below). Anything else is a truncated or
                # corrupt moments file — restoring it would silently zero or
                # garble optimizer state.
                want = self._io_len(i) * 4
                legacy = 2 * self.sizes[i] * 4
                have = os.path.getsize(src)
                if have not in (want, legacy):
                    raise RuntimeError(
                        f"corrupt moments file {src}: {have} bytes, expected "
                        f"{want} (or legacy {legacy}) — the checkpoint is "
                        "damaged; restore from the previous 'latest' tag")
                shutil.copy2(src, f)
                if have < want:
                    with open(f, "ab") as fh:
                        fh.write(b"\0" * (want - have))
                self._dirty[i] = True
            else:
                # leaf absent from the checkpoint = it was all-zeros when saved;
                # clearing dirty makes the next fetch zero-fill instead of reading
                # this run's stale on-disk moments
                self._dirty[i] = False

    # ------------------------------------------------------------------ checkpoint
    def read_moments(self):
        """Materialise all moments in host RAM — tests/small models only; the
        engine's checkpoint path streams via :meth:`copy_files_to` instead."""
        from ...ops.aio.aio_handle import aligned_array
        ms, vs = [], []
        for i, s in enumerate(self.sizes):
            mv = aligned_array(self._io_len(i) * 4, np.float32)
            mv[:] = 0.0
            if self._dirty[i]:
                self.handle.sync_pread(mv[:self._io_len(i)], self._files[i])
            ms.append(mv[:s].copy())
            vs.append(mv[s:2 * s].copy())
        return ms, vs

    def write_moments(self, ms, vs):
        from ...ops.aio.aio_handle import aligned_array
        for i, (m, v) in enumerate(zip(ms, vs)):
            s = self.sizes[i]
            mv = aligned_array(self._io_len(i) * 4, np.float32)
            mv[:s] = np.asarray(m, np.float32).reshape(-1)
            mv[s:2 * s] = np.asarray(v, np.float32).reshape(-1)
            mv[2 * s:] = 0.0
            self.handle.sync_pwrite(mv[:self._io_len(i)], self._files[i])
            self._dirty[i] = True  # the next _fetch must READ, not zero-fill


class OffloadOptimizerTier:
    """Host fp32 masters + moments; device params in compute dtype.

    ``kind`` is "adam" (AdamW via ``adam_w_mode``) or "adagrad" — the two reference CPU
    optimizers (``ops/adam/cpu_adam.py``, ``ops/adagrad/cpu_adagrad.py``).

    ``nvme_path`` adds the ZeRO-Infinity tier (reference
    ``runtime/swap_tensor/partitioned_optimizer_swapper.py:35`` + ``csrc/aio``): Adam
    moments live on disk, streamed through two double-buffered scratch arrays by the
    native async-I/O handle — next leaf's read overlaps the current leaf's SIMD Adam —
    so host RAM holds masters + 2 scratch buffers instead of masters + 2×params of
    moments.
    """

    def __init__(self, params_device: Any, param_shardings: Any, compute_dtype,
                 kind: str = "adam", betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adam_w_mode: bool = True,
                 bias_correction: bool = True, nvme_path: Optional[str] = None,
                 aio_config: Optional[dict] = None, grad_shardings: Any = None):
        leaves, self._treedef = jax.tree_util.tree_flatten(params_device)
        self._shardings = jax.tree_util.tree_leaves(
            param_shardings, is_leaf=lambda x: hasattr(x, "spec"))
        if not (len(self._shardings) == len(leaves)):
            raise AssertionError('len(self._shardings) == len(leaves)')
        self._shapes = [tuple(l.shape) for l in leaves]
        self.compute_dtype = compute_dtype
        self.kind = kind
        self._partitioned = jax.process_count() > 1
        if self._partitioned:
            if not (grad_shardings is not None):
                raise AssertionError("multi-process offload needs the gradient shardings (the layout " \
                "gradients arrive in is the layout masters partition along)")
            self._grad_shardings = jax.tree_util.tree_leaves(
                grad_shardings, is_leaf=lambda x: hasattr(x, "spec"))
            # materialise fp32 params in the GRADIENT layout: each process keeps only
            # the unique shards its devices own (reference: per-rank fp32 partition,
            # stage_1_and_2.py single_partition_of_fp32_groups)
            self._to_grad_layout = jax.jit(
                lambda p: jax.tree_util.tree_map(
                    lambda x: x.astype(jax.numpy.float32), p),
                out_shardings=jax.tree_util.tree_unflatten(
                    self._treedef, self._grad_shardings))
            grad_layout = self._to_grad_layout(params_device)
            gl_leaves = jax.tree_util.tree_leaves(grad_layout)
            self._slice_index: List[List[tuple]] = []
            self.masters = []
            self._leaf_slice_range: List[tuple] = []
            for l in gl_leaves:
                pairs = unique_local_shards(l)
                self._slice_index.append([k for k, _ in pairs])
                start = len(self.masters)
                self.masters.extend(
                    np.array(d, dtype=np.float32, copy=True).reshape(-1)
                    for _, d in pairs)
                self._leaf_slice_range.append((start, len(self.masters)))
            del grad_layout
            if nvme_path is not None:
                # per-process moment files: nvme_path may be shared storage
                nvme_path = os.path.join(nvme_path, f"proc{jax.process_index()}")
        else:
            # one D2H gather of the freshly-initialised (sharded) fp32 params
            for l in leaves:
                l.copy_to_host_async()
            # np.array(copy=True): np.asarray of a jax array is a READ-ONLY view of
            # jax-owned host memory — masters must be private writable buffers.
            self.masters: List[np.ndarray] = [
                np.array(l, dtype=np.float32, copy=True).reshape(-1) for l in leaves]
        self.nvme = None
        if kind == "adam" and nvme_path is not None:
            self.nvme = _NVMeMomentStore(nvme_path, self.masters,
                                         aio_config or {})
            self._adam_kwargs = dict(betas=betas, eps=eps,
                                     weight_decay=weight_decay,
                                     adam_w_mode=adam_w_mode,
                                     bias_correction=bias_correction)
            self.step_count = 0
        elif kind == "adam":
            self.opt = DeepSpeedCPUAdam(self.masters, betas=betas, eps=eps,
                                        weight_decay=weight_decay,
                                        adamw_mode=adam_w_mode,
                                        bias_correction=bias_correction)
            # DeepSpeedCPUAdam flattens-with-copy only if needed; masters are already flat
            # fp32 contiguous so these are shared views:
            self.masters = self.opt.params
        elif kind == "adagrad":
            self.eps, self.weight_decay = eps, weight_decay
            self.sq_sum = [np.zeros_like(p) for p in self.masters]
            self.step_count = 0
        else:
            raise ValueError(f"offload optimizer kind {kind!r} not supported "
                             "(adam/adamw/adagrad)")
        log_dist(f"ZeRO-Offload: {sum(p.size for p in self.masters):,} master params on "
                 f"host ({'native SIMD' if native_available() else 'numpy fallback'} "
                 f"{kind})", ranks=[0])

    # ------------------------------------------------------------------ device push
    def _cast_host(self, flat: np.ndarray, shape) -> np.ndarray:
        return cast_master_to(flat, shape, self.compute_dtype)

    def _push_leaf(self, i: int):
        """One leaf master → device (async dispatch), cast + placed per its spec.
        Shared by the full push and the interleaved per-leaf path in :meth:`step`."""
        return jax.device_put(self._cast_host(self.masters[i], self._shapes[i]),
                              self._shardings[i])

    def _push(self) -> Any:
        """Masters → device, cast to compute dtype, placed per param shardings."""
        if self._partitioned:
            return self._push_partitioned()
        outs = [self._push_leaf(i) for i in range(len(self.masters))]
        return jax.tree_util.tree_unflatten(self._treedef, outs)

    def _push_partitioned(self) -> Any:
        """Per-process master slices → grad-layout device arrays → one jitted reshard
        into the param layout (XLA all-gathers over ICI — the analogue of the
        reference's post-step ``all_gather_dp_groups``)."""
        outs = []
        for li, (shape, gsh) in enumerate(zip(self._shapes, self._grad_shardings)):
            start, _ = self._leaf_slice_range[li]
            by_idx = {k: self.masters[start + j]
                      for j, k in enumerate(self._slice_index[li])}
            singles = []
            for dev, index in gsh.addressable_devices_indices_map(shape).items():
                key = _norm_index(index, shape)
                sl_shape = tuple(b - a for a, b in key)
                singles.append(jax.device_put(
                    self._cast_host(by_idx[key], sl_shape), dev))
            outs.append(jax.make_array_from_single_device_arrays(shape, gsh, singles))
        tree = jax.tree_util.tree_unflatten(self._treedef, outs)
        if not hasattr(self, "_reshard_fn"):
            self._reshard_fn = jax.jit(
                lambda t: t, out_shardings=jax.tree_util.tree_unflatten(
                    self._treedef, self._shardings))
        return self._reshard_fn(tree)

    def initial_device_params(self) -> Any:
        return self._push()

    # ------------------------------------------------------------------ step
    def step(self, grads_device: Any, lr: float, skip: bool = False) -> Optional[Any]:
        """Host optimizer step from device grads; returns new device params
        (or None when ``skip`` — fp16 overflow — so the caller keeps the old ones)."""
        if skip:
            return None
        leaves = jax.tree_util.tree_leaves(grads_device)
        for l in leaves:
            l.copy_to_host_async()
        if self._partitioned:
            grads = []
            for li, l in enumerate(leaves):
                pairs = unique_local_shards(l)
                if not ([k for k, _ in pairs] == self._slice_index[li]):
                    raise AssertionError("gradient sharding drifted from the masters partition")
                grads.extend(np.asarray(d, dtype=np.float32).reshape(-1)
                             for _, d in pairs)
        else:
            grads = [np.asarray(l, dtype=np.float32).reshape(-1) for l in leaves]
        if self.nvme is not None:
            self.step_count += 1
            self.nvme.adam_step_all(self.masters, grads, lr, self.step_count,
                                    **self._adam_kwargs)
            return self._push()
        if self._partitioned:
            if self.kind == "adam":
                self.opt.step(grads, lr=lr)
            else:
                self.step_count += 1
                for p, s, g in zip(self.masters, self.sq_sum, grads):
                    adagrad_step(p, s, g, lr, self.eps, self.weight_decay)
            return self._push()
        # single-process RAM tier: interleave the async H2D push of leaf i with the
        # SIMD update of leaf i+1 (reference cpu_adam.cpp tiles copy/compute; the
        # round-2 review flagged the lockstep update-then-push as critical-path cost)
        outs: List[Any] = [None] * len(self.masters)

        def push_leaf(i: int):
            outs[i] = self._push_leaf(i)

        if self.kind == "adam":
            self.opt.step(grads, lr=lr, on_leaf_done=push_leaf)
        else:
            self.step_count += 1
            for i, (p, s, g) in enumerate(zip(self.masters, self.sq_sum, grads)):
                adagrad_step(p, s, g, lr, self.eps, self.weight_decay)
                push_leaf(i)
        return jax.tree_util.tree_unflatten(self._treedef, outs)

    def reseed_from_device(self, params_device: Any):
        """Overwrite masters from (compute-dtype) device params — fallback when loading a
        checkpoint written by a non-offload engine."""
        if self._partitioned:
            grad_layout = self._to_grad_layout(params_device)
            i = 0
            for li, l in enumerate(jax.tree_util.tree_leaves(grad_layout)):
                for _, d in unique_local_shards(l):
                    np.copyto(self.masters[i],
                              np.asarray(d, dtype=np.float32).reshape(-1))
                    i += 1
            return
        leaves = jax.tree_util.tree_leaves(params_device)
        for dst, l in zip(self.masters, leaves):
            np.copyto(dst, np.asarray(l, dtype=np.float32).reshape(-1))

    # ------------------------------------------------------------------ checkpoint
    def has_checkpoint(self, path: str) -> bool:
        """True when ``path`` holds this tier's saved state in the CURRENT mode
        (partitioned mode writes per-process ``.npz`` files, not a directory).
        A checkpoint from the OTHER mode (or another process count) raises instead of
        silently falling back to reseed-from-device — like the reference, resuming a
        ZeRO run needs a matching partition layout."""
        import glob
        part_files = glob.glob(path + "_part*.npz")
        if self._partitioned:
            if os.path.isfile(path + f"_part{jax.process_index()}.npz"):
                return True
            if os.path.isdir(path) or part_files:
                raise RuntimeError(
                    f"offload checkpoint at {path} was written by a different "
                    f"process layout (found {'directory' if os.path.isdir(path) else part_files}); "
                    "resume with the topology that wrote it, or load with "
                    "load_optimizer_states=False to discard optimizer state explicitly")
            return False
        if os.path.isdir(path):
            return True
        if part_files:
            raise RuntimeError(
                f"offload checkpoint at {path} holds multi-process partition files "
                f"{part_files}; resume with the process count that wrote them, or "
                "load with load_optimizer_states=False to discard optimizer state")
        return False

    def save_to(self, checkpoint_engine, path: str):
        """Engine checkpoint hook. NVMe mode streams moments by file copy (no RAM
        materialisation); RAM mode serialises the full state dict. Multi-process mode
        writes one partition file per process (reference: per-rank
        ``zero_pp_rank_*`` files, ``engine.py _save_zero_checkpoint``) — resume
        requires the same grad sharding, like the reference requires matching dp size."""
        if self._partitioned:
            fn = path + f"_part{jax.process_index()}.npz"
            data = {f"master_{i}": m for i, m in enumerate(self.masters)}
            if self.nvme is not None:
                data["step"] = np.asarray(self.step_count, dtype=np.int64)
                self.nvme.copy_files_to(path + f"_moments_p{jax.process_index()}")
            elif self.kind == "adam":
                sd = self.opt.state_dict()
                data["step"] = np.asarray(sd["step"], dtype=np.int64)
                for i, (m, v) in enumerate(zip(sd["m"], sd["v"])):
                    data[f"m_{i}"], data[f"v_{i}"] = m, v
            else:
                data["step"] = np.asarray(self.step_count, dtype=np.int64)
                for i, s in enumerate(self.sq_sum):
                    data[f"sq_{i}"] = s
            np.savez(fn, **data)
            return
        if self.nvme is not None:
            import os
            light = {"masters": {f"leaf{i}": m.reshape(self._shapes[i])
                                 for i, m in enumerate(self.masters)},
                     "step": np.asarray(self.step_count, dtype=np.int64)}
            checkpoint_engine.save(light, path)
            self.nvme.copy_files_to(path + "_moments")
            return
        checkpoint_engine.save(self.state_dict(), path)

    def load_from(self, checkpoint_engine, path: str):
        import os
        if self._partitioned:
            fn = path + f"_part{jax.process_index()}.npz"
            with np.load(fn) as data:
                for i, m in enumerate(self.masters):
                    np.copyto(m, data[f"master_{i}"])
                if self.nvme is not None:
                    self.step_count = int(data["step"])
                elif self.kind == "adam":
                    n = len(self.masters)
                    self.opt.load_state_dict({
                        "step": int(data["step"]),
                        "m": [data[f"m_{i}"] for i in range(n)],
                        "v": [data[f"v_{i}"] for i in range(n)]})
                else:
                    self.step_count = int(data["step"])
                    for i, s in enumerate(self.sq_sum):
                        np.copyto(s, data[f"sq_{i}"])
            if self.nvme is not None:
                self.nvme.copy_files_from(path + f"_moments_p{jax.process_index()}")
            return
        if self.nvme is not None:
            light = {"masters": {f"leaf{i}": m.reshape(self._shapes[i])
                                 for i, m in enumerate(self.masters)},
                     "step": np.asarray(0, dtype=np.int64)}
            restored = checkpoint_engine.load(path, template=light)
            for i, m in enumerate(self.masters):
                np.copyto(m, np.asarray(restored["masters"][f"leaf{i}"],
                                        dtype=np.float32).reshape(-1))
            self.step_count = int(restored["step"])
            self.nvme.copy_files_from(path + "_moments")
            return
        self.load_state_dict(checkpoint_engine.load(path,
                                                    template=self.state_dict()))

    def state_dict(self) -> dict:
        if not (not self._partitioned):
            raise AssertionError("multi-process tier checkpoints via save_to/load_from partition files")
        shapes = {f"leaf{i}": np.asarray(s, dtype=np.int64)
                  for i, s in enumerate(self._shapes)}
        sd = {"masters": {f"leaf{i}": m.reshape(self._shapes[i])
                          for i, m in enumerate(self.masters)},
              "shapes": shapes}
        if self.nvme is not None:
            ms, vs = self.nvme.read_moments()
            sd["m"] = {f"leaf{i}": m.reshape(self._shapes[i])
                       for i, m in enumerate(ms)}
            sd["v"] = {f"leaf{i}": v.reshape(self._shapes[i])
                       for i, v in enumerate(vs)}
            sd["step"] = np.asarray(self.step_count, dtype=np.int64)
        elif self.kind == "adam":
            opt_sd = self.opt.state_dict()
            sd["m"] = {f"leaf{i}": m.reshape(self._shapes[i])
                       for i, m in enumerate(opt_sd["m"])}
            sd["v"] = {f"leaf{i}": v.reshape(self._shapes[i])
                       for i, v in enumerate(opt_sd["v"])}
            sd["step"] = np.asarray(opt_sd["step"], dtype=np.int64)
        else:
            sd["sq_sum"] = {f"leaf{i}": s.reshape(self._shapes[i])
                            for i, s in enumerate(self.sq_sum)}
            sd["step"] = np.asarray(self.step_count, dtype=np.int64)
        return sd

    def load_state_dict(self, sd: dict):
        for i, m in enumerate(self.masters):
            np.copyto(m, np.asarray(sd["masters"][f"leaf{i}"],
                                    dtype=np.float32).reshape(-1))
        if self.nvme is not None:
            self.step_count = int(sd["step"])
            self.nvme.write_moments(
                [np.asarray(sd["m"][f"leaf{i}"]) for i in range(len(self.masters))],
                [np.asarray(sd["v"][f"leaf{i}"]) for i in range(len(self.masters))])
        elif self.kind == "adam":
            self.opt.load_state_dict({
                "step": int(sd["step"]),
                "m": [np.asarray(sd["m"][f"leaf{i}"]) for i in range(len(self.masters))],
                "v": [np.asarray(sd["v"][f"leaf{i}"]) for i in range(len(self.masters))],
            })
        else:
            self.step_count = int(sd["step"])
            for i, s in enumerate(self.sq_sum):
                np.copyto(s, np.asarray(sd["sq_sum"][f"leaf{i}"],
                                        dtype=np.float32).reshape(-1))
