"""ZeRO configuration.

TPU-native analogue of reference ``deepspeed/runtime/zero/config.py`` (``DeepSpeedZeroConfig``,
``ZeroStageEnum`` at ``zero/config.py:70,79``) and ``zero/offload_config.py``.

On TPU, ZeRO stages map onto sharding specifications over the combined ``data``×``fsdp`` mesh
axes rather than autograd-hook machinery:

- stage 0: params/grads/optimizer replicated over data axis (plain DP; XLA psums grads).
- stage 1: optimizer state sharded over the data axis.
- stage 2: + gradients stored sharded (XLA emits reduce-scatter instead of all-reduce).
- stage 3: + parameters sharded (FSDP-style); XLA inserts just-in-time all-gathers which it
  overlaps with compute — the analogue of the reference's prefetching param coordinator.

Most tuning knobs of the reference (bucket sizes, prefetch counts, persistence thresholds) do
not exist on TPU because XLA schedules the collectives; they are accepted and ignored so configs
carry over.
"""

from enum import IntEnum
from typing import Optional

from pydantic import Field

from ...config.config_utils import ConfigModel


class ZeroStageEnum(IntEnum):
    """Reference ``zero/config.py:70``."""
    disabled = 0
    optimizer_states = 1
    gradients = 2
    weights = 3
    max_stage = 3


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(ConfigModel):
    """Reference ``zero/offload_config.py:DeepSpeedZeroOffloadParamConfig``."""
    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(int(1e8), ge=0)
    max_in_cpu: int = Field(int(1e9), ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(ConfigModel):
    """Reference ``zero/offload_config.py:DeepSpeedZeroOffloadOptimizerConfig``."""
    device: str = "none"  # none | cpu | nvme
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False

    @property
    def pipeline(self) -> bool:
        return self.pipeline_read or self.pipeline_write


class DeepSpeedZeroConfig(ConfigModel):
    """Reference ``zero/config.py:79`` — same JSON keys under ``"zero_optimization"``."""
    stage: int = Field(0, ge=0, le=3)
    contiguous_gradients: bool = True          # ignored: XLA owns layout
    reduce_scatter: bool = True                # implied by stage>=2 sharding on TPU
    reduce_bucket_size: int = Field(int(5e8), ge=0)   # ignored: XLA buckets collectives
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(int(5e8), ge=0)
    overlap_comm: Optional[bool] = None        # XLA latency-hiding scheduler handles overlap
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = Field(int(1e9), ge=0)
    cpu_offload_param: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_param"})
    cpu_offload_use_pin_memory: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True})
    cpu_offload: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_optimizer"})
    prefetch_bucket_size: int = Field(int(5e7), ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(int(1e5), ge=0,
                                             alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(int(1e15), ge=0,
                                             alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(int(1e9), ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(int(1e9), ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(
        False, alias="stage3_gather_16bit_weights_on_model_save")
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False        # ignored: no flat-buffer partitioning on TPU

    def __init__(self, **data):
        if data.get("cpu_offload"):
            data.setdefault("offload_optimizer", {"device": "cpu"})
        if data.get("cpu_offload_param"):
            data.setdefault("offload_param", {"device": "cpu"})
        super().__init__(**data)
