"""ZeRO as sharding policy.

This module is the TPU-native replacement for the reference's entire ZeRO mechanism layer
(``zero/stage_1_and_2.py``, ``zero/stage3.py``, ``zero/partition_parameters.py``,
``zero/partitioned_param_coordinator.py`` — ~7.3k LoC of hook/bucket/stream machinery):

- stage 1 → optimizer state carries a PartitionSpec over the ``fsdp`` axis; XLA computes the
  Adam update shard-locally and all-gathers updated params (the reference's
  ``all_gather_dp_groups`` hot spot, compiler-scheduled).
- stage 2 → the gradient accumulator carries the same sharded spec, so XLA lowers each
  microbatch's gradient sum to reduce-scatter instead of all-reduce (the reference's
  ``reduce_ipg_grads``/``average_tensor`` bucket loop).
- stage 3 → parameters themselves carry the spec; XLA inserts just-in-time all-gathers per
  consumer and frees gathered copies after use, overlapping with compute via the
  latency-hiding scheduler (the reference's ``PartitionedParameterCoordinator`` prefetching).

The policy below decides, per tensor, which dimension shards over ``fsdp`` (largest divisible
dim, preferring dims not already sharded by tensor parallelism) and which tensors stay
replicated (smaller than ``param_persistence_threshold``, matching stage-3 persistence
semantics in ``zero/config.py``).
"""

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...parallel.mesh import AXIS_FSDP, MeshSpec


def _spec_axes(spec: Optional[P]):
    if spec is None:
        return []
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append(tuple(entry))
        else:
            out.append((entry,))
    return out


def infer_fsdp_spec(shape, fsdp_size: int, base_spec: Optional[P] = None,
                    min_size: int = 0) -> P:
    """Choose the dim to shard over ``fsdp`` for one tensor.

    Rules: skip scalars; skip tensors with fewer than ``min_size`` elements (persistence
    threshold, reference ``stage3_param_persistence_threshold``); among dims whose size is
    divisible by ``fsdp_size`` and not already sharded by ``base_spec`` (TP), pick the largest;
    if none divides evenly, replicate (correctness first — XLA cannot shard unevenly without
    padding specs).
    """
    shape = tuple(shape)
    base = _spec_axes(base_spec)
    base = base + [None] * (len(shape) - len(base))
    if any(entry is not None and AXIS_FSDP in entry for entry in base):
        return P(*base)  # already fsdp-sharded (e.g. stage-3 param spec reused as base)
    if fsdp_size <= 1 or len(shape) == 0 or int(np.prod(shape)) < min_size:
        return P(*base) if base_spec is not None else P()
    best_dim, best_size = -1, 0
    for d, sz in enumerate(shape):
        if base[d] is not None:
            continue  # dim already sharded (e.g. by TP); keep fsdp off it
        if sz % fsdp_size == 0 and sz > best_size:
            best_dim, best_size = d, sz
    if best_dim < 0:
        return P(*base) if base_spec is not None else P()
    new = list(base)
    new[best_dim] = (AXIS_FSDP,)
    return P(*[tuple(e) if e else None for e in new])


def param_specs(abstract_params: Any, mesh_spec: MeshSpec, zero_stage: int,
                base_specs: Any = None, persistence_threshold: int = 0) -> Any:
    """PartitionSpec pytree for master parameters.

    ``base_specs`` optionally carries model-declared TP/pipeline specs to merge with.
    """
    fsdp = mesh_spec.size(AXIS_FSDP)

    def one(leaf, base):
        shape = getattr(leaf, "shape", ())
        if zero_stage >= 3:
            return infer_fsdp_spec(shape, fsdp, base, min_size=persistence_threshold)
        return base if base is not None else P()

    if base_specs is None:
        return jax.tree_util.tree_map(lambda l: one(l, None), abstract_params)
    return jax.tree_util.tree_map(one, abstract_params, base_specs)


def optimizer_state_specs(abstract_opt_state: Any, mesh_spec: MeshSpec,
                          zero_stage: int, abstract_params: Any = None,
                          param_spec_tree: Any = None) -> Any:
    """PartitionSpec pytree for optimizer state: sharded from stage 1 up.

    Scalars (step counters) replicate. Moment tensors inherit the parameter's sharding
    (pipe/TP/stage-3 fsdp) — matched by shape, since optimizer states mirror the param tree
    leaf-for-leaf — and from stage 1 additionally shard a free dim over ``fsdp``.
    """
    fsdp = mesh_spec.size(AXIS_FSDP)

    def finalize(leaf, base):
        shape = tuple(getattr(leaf, "shape", ()))
        if zero_stage >= 1 and len(shape) > 0:
            return infer_fsdp_spec(shape, fsdp, base)
        if base is not None and len(shape) > 0:
            return base
        return P()

    if abstract_params is None or param_spec_tree is None:
        return jax.tree_util.tree_map(lambda l: finalize(l, None), abstract_opt_state)

    # Optimizer moments mirror the param tree leaf-for-leaf (e.g. AdamState.exp_avg): match
    # by TREE STRUCTURE, which is exact — shape-based matching would confuse same-shaped
    # params with different specs.
    param_treedef = jax.tree_util.tree_structure(abstract_params)

    def mirrors_params(subtree) -> bool:
        try:
            return jax.tree_util.tree_structure(subtree) == param_treedef
        except Exception:
            return False

    def handle(subtree):
        if mirrors_params(subtree):
            return jax.tree_util.tree_map(finalize, subtree, param_spec_tree,
                                          is_leaf=lambda x: isinstance(x, P))
        return jax.tree_util.tree_map(lambda l: finalize(l, None), subtree)

    return jax.tree_util.tree_map(handle, abstract_opt_state, is_leaf=mirrors_params)


def grad_accum_specs(abstract_params: Any, mesh_spec: MeshSpec, zero_stage: int,
                     param_base_specs: Any = None) -> Any:
    """PartitionSpec pytree for the gradient accumulator (stage >= 2 shards it)."""
    fsdp = mesh_spec.size(AXIS_FSDP)

    def one(leaf, base=None):
        shape = getattr(leaf, "shape", ())
        if zero_stage >= 2:
            return infer_fsdp_spec(shape, fsdp, base)
        return base if base is not None else P()

    if param_base_specs is None:
        return jax.tree_util.tree_map(one, abstract_params)
    return jax.tree_util.tree_map(one, abstract_params, param_base_specs)


def to_shardings(spec_tree: Any, mesh_spec: MeshSpec) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh_spec.mesh, s),
        spec_tree, is_leaf=lambda x: isinstance(x, P))
