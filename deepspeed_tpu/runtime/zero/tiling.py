"""Tiled linear layers — memory-efficient huge matmuls.

TPU-native re-design of reference ``deepspeed/runtime/zero/tiling.py``
(``TiledLinear:22``): the reference splits one huge ``nn.Linear`` into an
``in_splits × out_splits`` grid of small Linears so ZeRO-3 can fetch/partition one tile
at a time. On TPU the same decomposition serves the same masters:

- each tile is its OWN parameter leaf → ZeRO-3/fsdp shards and the offload tiers
  stream tiles independently (a 50k×8k vocab projection becomes 8 × 50k×1k leaves
  instead of one 1.6 GB tensor that must be resident whole);
- XLA still fuses the per-tile matmuls into efficient MXU work — the tiling costs
  nothing at compile time (unlike the reference, which pays python-loop overhead).

:func:`chunked_vocab_cross_entropy` is the capability the reference uses TiledLinear
for in practice (the LM head): cross-entropy against a huge vocabulary WITHOUT ever
materialising the full ``(b, t, V)`` logits — a ``lax.scan`` over vocab chunks carries
running ``logsumexp`` and target scores, so peak memory is ``O(b·t·chunk)``.
"""

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class TiledDense(nn.Module):
    """Drop-in ``nn.Dense`` with the kernel stored as an ``in_splits × out_splits``
    grid of independent tiles (reference ``TiledLinear.__init__`` partitioning via
    ``partition_uniform``). Uneven dims split as evenly as possible.

    Math is EXACTLY ``x @ W + b`` with ``W = concat(tiles)``; only the parameter
    layout changes.
    """
    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Any = None
    # None → lecun-normal CORRECTED for the tiling: variance_scaling already divides
    # by each tile's fan_in (= fan_in/in_splits), so one partial's output variance
    # matches the monolithic Dense; summing in_splits independent partials then
    # multiplies variance by in_splits — scale 1/in_splits restores Dense's stats
    kernel_init: Optional[Callable] = None
    bias_init: Callable = nn.initializers.zeros

    @staticmethod
    def _bounds(total: int, splits: int):
        cuts = [round(i * total / splits) for i in range(splits + 1)]
        return list(zip(cuts[:-1], cuts[1:]))

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        in_b = self._bounds(in_dim, self.in_splits)
        out_b = self._bounds(self.features, self.out_splits)
        dt = self.dtype or x.dtype
        kinit = self.kernel_init or nn.initializers.variance_scaling(
            1.0 / self.in_splits, "fan_in", "truncated_normal")
        outs = []
        for oi, (o0, o1) in enumerate(out_b):
            acc = None
            for ii, (i0, i1) in enumerate(in_b):
                k = self.param(f"kernel_{ii}_{oi}", kinit,
                               (i1 - i0, o1 - o0), jnp.float32)
                part = x[..., i0:i1].astype(dt) @ k.astype(dt)
                acc = part if acc is None else acc + part
            if self.use_bias:
                b = self.param(f"bias_{oi}", self.bias_init, (o1 - o0,), jnp.float32)
                acc = acc + b.astype(dt)
            outs.append(acc)
        return jnp.concatenate(outs, axis=-1)


def tiled_kernel_from_dense(kernel: np.ndarray, in_splits: int, out_splits: int,
                            bias: Optional[np.ndarray] = None) -> dict:
    """Convert a monolithic flax Dense kernel (+bias) into the TiledDense param tree
    (reference ``TiledLinear.copy_params_from``)."""
    in_dim, out_dim = kernel.shape
    in_b = TiledDense._bounds(in_dim, in_splits)
    out_b = TiledDense._bounds(out_dim, out_splits)
    p = {}
    for oi, (o0, o1) in enumerate(out_b):
        for ii, (i0, i1) in enumerate(in_b):
            p[f"kernel_{ii}_{oi}"] = jnp.asarray(kernel[i0:i1, o0:o1])
        if bias is not None:
            p[f"bias_{oi}"] = jnp.asarray(bias[o0:o1])
    return p


def chunked_vocab_cross_entropy(x: jnp.ndarray, wte: jnp.ndarray,
                                labels: jnp.ndarray, chunk: int = 8192,
                                ignore_index: int = -100,
                                compute_dtype=None) -> jnp.ndarray:
    """Mean next-token cross-entropy against a TIED embedding head without
    materialising ``(b, t, V)`` logits.

    ``x``: final hidden states ``(b, t, d)`` (already layernormed); ``wte``:
    ``(V, d)``; ``labels``: ``(b, t)`` with ``ignore_index`` masking. A scan over
    ``V/chunk`` vocab slices carries running max/sumexp (online logsumexp — the same
    recurrence flash attention uses over keys) and picks each position's target score
    when its token falls inside the slice. Peak memory ``O(b·t·chunk)``.

    ``compute_dtype`` (e.g. bf16) sets the head matmul's operand dtype with fp32
    MXU accumulation — the same full-rate-matmul treatment the monolithic tied
    head uses (an fp32 matmul runs at ~1/4 MXU rate and the head is ~25% of a
    small model's FLOPs); the logsumexp carry stays fp32 either way.
    """
    b, t, d = x.shape
    V = wte.shape[0]
    pad = (-V) % chunk
    n_chunks = (V + pad) // chunk
    cd = compute_dtype or jnp.float32
    labels_flat = labels.reshape(-1)
    xf = x.astype(cd).reshape(-1, d)                         # (N, d)
    wte_p = jnp.pad(wte.astype(cd), ((0, pad), (0, 0)))

    def body(carry, ci):
        m, s, tgt = carry
        w = jax.lax.dynamic_slice(wte_p, (ci * chunk, 0), (chunk, d))
        logits = jax.lax.dot_general(
            xf, w, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (N, chunk) fp32
        # padded vocab rows are embedding zeros → logit 0 for every position; mask
        cols = ci * chunk + jnp.arange(chunk)
        logits = jnp.where(cols[None, :] < V, logits, -1e30)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(logits - m_new[:, None]),
                                             axis=-1)
        # target score if this chunk holds the label
        in_chunk = (labels_flat >= ci * chunk) & (labels_flat < (ci + 1) * chunk)
        idx = jnp.clip(labels_flat - ci * chunk, 0, chunk - 1)
        score = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        tgt = jnp.where(in_chunk, score, tgt)
        return (m_new, s, tgt), None

    m0 = jnp.full((xf.shape[0],), -1e30, jnp.float32)
    s0 = jnp.zeros((xf.shape[0],), jnp.float32)
    tgt0 = jnp.zeros((xf.shape[0],), jnp.float32)
    (m, s, tgt), _ = jax.lax.scan(body, (m0, s0, tgt0), jnp.arange(n_chunks))
    nll = (m + jnp.log(s)) - tgt                             # logsumexp - target
    mask = labels_flat != ignore_index
    denom = jnp.maximum(jnp.sum(mask), 1)
    return jnp.sum(jnp.where(mask, nll, 0.0)) / denom
