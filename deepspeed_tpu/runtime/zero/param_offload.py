"""ZeRO-3 parameter offload: host-resident parameters streamed per layer-group.

TPU-native re-design of the reference's stage-3 parameter offload
(``runtime/zero/partition_parameters.py:539`` host-partitioned params,
``partitioned_param_coordinator.py:239`` fetch/prefetch under autograd hooks,
``swap_tensor/partitioned_param_swapper.py:35`` NVMe tier): the model that cannot fit in
HBM lives in host RAM as fp32 masters; the train step becomes an explicit stream over the
model's :class:`~...models.base.Segment` decomposition:

- **forward**: segments run in order; while segment *g* computes, segment *g+1*'s
  parameters are already in flight H2D (``jax.device_put`` dispatch is async — the
  double-buffer analogue of the reference's ``__prefetch_nvme_param_partitions``).
  Only boundary activations are kept on device.
- **backward**: segments run in reverse with the same 2-deep streaming window; each
  segment's VJP *recomputes* its forward internally (segment-granular rematerialisation —
  the reference pairs offload with activation checkpointing for the same reason).
  Parameter gradients leave the device immediately (async D2H) and accumulate into host
  fp32 buffers, overlapping the previous segment's backward compute.
- **update**: the native SIMD Adam (``ops/csrc/adam/cpu_adam.cpp``) updates the masters in
  place; there is no in-HBM optimizer state at all. With ``nvme_path`` the Adam moments
  live on disk, double-buffered through the async-I/O handle (ZeRO-Infinity). With
  ``nvme_param_path`` (``offload_param.device='nvme'``) the fp32 masters AND gradient
  accumulators live on disk too: host RAM is bounded by the double-buffer scratch
  (O(largest leaf)), independent of model size — the full "1T parameters on a node"
  half of ZeRO-Infinity (reference ``swap_tensor/partitioned_param_swapper.py:35``).

Peak HBM ≈ 2 segment param slices + boundary activations + one segment's gradients —
independent of total model size, which is the reference's "40B on one V100" recipe
re-based onto one TPU chip.

Multi-process runs partition the masters per process along the GRADIENT layout (dim-0
sharded over the dp axes where divisible): each process initialises, accumulates and
updates only its devices' unique shards, and the push reconstructs the grad layout and
reshards to replicated via one jitted all-gather per key (the optimizer tier's recipe
applied to the streaming tier; reference per-rank cpu offload, ``stage_1_and_2.py:130``).
"""

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.base import Segment
from ...ops.adam.cpu_adam import DeepSpeedCPUAdam, adagrad_step, native_available
from ...utils.logging import log_dist
from ..fp16.loss_scaler import DynamicLossScaler, LossScaleState


def _leaf_dotted_names(key: str, treedef) -> List[str]:
    """Dotted reference-style names of a segment's leaves, in tree-leaf order —
    the same names ``checkpoint.export._dotted_tree`` produces for the full tree."""
    dummy = jax.tree_util.tree_unflatten(treedef, list(range(treedef.num_leaves)))
    flat, _ = jax.tree_util.tree_flatten_with_path(dummy)
    names = [""] * treedef.num_leaves
    for path, leaf_i in flat:
        parts = [str(getattr(p, "key", getattr(p, "idx", p))) for p in path]
        names[leaf_i] = ".".join([key] + parts)
    return names


class _StreamCache:
    """2-deep window of device-resident segment parameter trees.

    ``prefetch`` dispatches the H2D copies without waiting; ``get`` returns the tree
    (pushing synchronously only on a prefetch miss); ``evict`` drops the reference so
    XLA frees the buffers once in-flight computations retire."""

    def __init__(self, push_fn):
        self._push = push_fn
        self._live: Dict[int, Any] = {}
        self.peak_live_bytes = 0
        self._live_bytes: Dict[int, int] = {}

    def prefetch(self, si: int):
        if si not in self._live:
            tree, nbytes = self._push(si)
            self._live[si] = tree
            self._live_bytes[si] = nbytes
            self.peak_live_bytes = max(self.peak_live_bytes,
                                       sum(self._live_bytes.values()))

    def get(self, si: int):
        self.prefetch(si)
        return self._live[si]

    def evict(self, si: int):
        self._live.pop(si, None)
        self._live_bytes.pop(si, None)

    def clear(self):
        self._live.clear()
        self._live_bytes.clear()


class _NVMeParamTier:
    """fp32 parameter masters + gradient accumulators on disk — the other half of
    ZeRO-Infinity (reference ``swap_tensor/partitioned_param_swapper.py:35`` param
    swapping, ``pipelined_optimizer_swapper.py:55`` read/compute/write overlap).

    Layout: one file per flat leaf (``masters_leaf{i}.bin`` / ``grads_leaf{i}.bin``)
    under ``path``, O_DIRECT through the native aio handle. Host RAM holds only the
    double-buffer scratch (4 × padded largest leaf), so with the moment store this
    tier bounds host memory by O(largest leaf) — independent of model size.
    """

    def __init__(self, path: str, sizes: List[int], aio_config: dict):
        import os
        from ...ops.aio.aio_handle import aligned_array, padded_len
        from .offload import make_swap_handle
        self.path = path
        self.sizes = list(sizes)
        n = len(self.sizes)
        self.handle = make_swap_handle(path, aio_config,
                                       "offload_param.device='nvme'")
        self._padded = lambda s: padded_len(s, 4)
        self._mfiles = [os.path.join(path, f"masters_leaf{i}.bin") for i in range(n)]
        self._gfiles = [os.path.join(path, f"grads_leaf{i}.bin") for i in range(n)]
        cap = self._padded(max(self.sizes))
        # 2 master + 2 grad double-buffers + 1 push/cast staging buffer
        self._mbuf = [aligned_array(cap * 4, np.float32) for _ in range(2)]
        self._gbuf = [aligned_array(cap * 4, np.float32) for _ in range(2)]
        self._pushbuf = aligned_array(cap * 4, np.float32)
        self.scratch_bytes = 5 * cap * 4
        self.grad_dirty = [False] * n
        self.leaf_sq = np.zeros(n, np.float64)

    # ------------------------------------------------------------------- masters
    def write_master(self, i: int, flat: np.ndarray):
        """Synchronous master write (init / checkpoint restore)."""
        s = self.sizes[i]
        buf = self._pushbuf
        buf[:s] = flat
        buf[s:self._padded(s)] = 0.0
        self.handle.sync_pwrite(buf[:self._padded(s)], self._mfiles[i])

    def read_master(self, i: int) -> np.ndarray:
        """Synchronous master read into the staging buffer (valid until the next
        push/read on this tier)."""
        s = self.sizes[i]
        try:
            self.handle.sync_pread(self._pushbuf[:self._padded(s)],
                                   self._mfiles[i])
        except OSError as e:
            raise RuntimeError(
                f"NVMe master read failed for leaf {i} ({self._mfiles[i]}): "
                f"{e} — the swap file is truncated or unreadable; restart from "
                "the last checkpoint") from e
        return self._pushbuf[:s]

    def read_masters_pipelined(self, indices):
        """Yield each leaf's flat fp32 master with one-leaf read-ahead: leaf j+1
        streams from disk while the consumer casts/pushes leaf j (the segment-push
        analogue of the update loop's ``fetch_mg`` double-buffering). Each yielded
        view is valid only until the next iteration — consumers must copy (cast)
        before advancing."""
        idx = list(indices)
        if not idx:
            return
        self.handle.async_pread(
            self._mbuf[0][:self._padded(self.sizes[idx[0]])], self._mfiles[idx[0]])
        self.handle.wait()
        for j, i in enumerate(idx):
            if j + 1 < len(idx):
                nxt = idx[j + 1]
                self.handle.async_pread(
                    self._mbuf[(j + 1) % 2][:self._padded(self.sizes[nxt])],
                    self._mfiles[nxt])
            yield self._mbuf[j % 2][:self.sizes[i]]
            self.handle.wait()

    # ------------------------------------------------------------------- grads
    def reset_grads(self):
        self.grad_dirty = [False] * len(self.sizes)
        self.leaf_sq[:] = 0.0

    def accumulate_leaf(self, i: int, contrib: np.ndarray):
        """accum[i] += contrib (read-modify-write through scratch); tracks the
        leaf's current sum-of-squares so the update pass needs no extra norm pass."""
        s = self.sizes[i]
        buf = self._gbuf[0]
        if self.grad_dirty[i]:
            self.handle.sync_pread(buf[:self._padded(s)], self._gfiles[i])
            acc = buf[:s]
            acc += contrib
        else:
            buf[:s] = contrib
            buf[s:self._padded(s)] = 0.0
            acc = buf[:s]
        self.leaf_sq[i] = np.dot(acc, acc)
        self.handle.sync_pwrite(buf[:self._padded(s)], self._gfiles[i])
        self.grad_dirty[i] = True

    # -------------------------------------------------------------------- update
    def fetch_mg(self, i: int, slot: int):
        """Async reads of leaf ``i``'s masters+grads into double-buffer ``slot``."""
        p = self._padded(self.sizes[i])
        self.handle.async_pread(self._mbuf[slot][:p], self._mfiles[i])
        self.handle.async_pread(self._gbuf[slot][:p], self._gfiles[i])

    def write_master_async(self, i: int, slot: int):
        self.handle.async_pwrite(
            self._mbuf[slot][:self._padded(self.sizes[i])], self._mfiles[i])

    # ---------------------------------------------------------------- streaming ckpt
    def copy_masters_to(self, dest_dir: str):
        import os
        import shutil
        os.makedirs(dest_dir, exist_ok=True)
        self.handle.wait()
        for f in self._mfiles:
            shutil.copy2(f, os.path.join(dest_dir, os.path.basename(f)))

    def copy_masters_from(self, src_dir: str):
        import os
        import shutil
        for i, f in enumerate(self._mfiles):
            src = os.path.join(src_dir, os.path.basename(f))
            want = self._padded(self.sizes[i]) * 4
            if not os.path.isfile(src):
                raise RuntimeError(
                    f"missing master file {src} in checkpoint — the checkpoint "
                    "is incomplete; restore from the previous 'latest' tag")
            have = os.path.getsize(src)
            if have != want:
                raise RuntimeError(
                    f"corrupt master file {src}: {have} bytes, expected {want} "
                    "— the checkpoint is damaged; restore from the previous "
                    "'latest' tag")
            shutil.copy2(src, f)


class ParamOffloadCoordinator:
    """Host fp32 masters for the WHOLE model + streamed segment execution.

    Owns the optimizer (host Adam/Adagrad — parameter offload implies the optimizer tier:
    if the parameters don't fit in HBM, the optimizer state certainly doesn't) and the
    fp16 loss scaler. The engine delegates ``train_batch``/``eval_batch``/checkpoint to
    this object when ``zero_optimization.offload_param`` is enabled.
    """

    def __init__(self, segments: List[Segment], rng, compute_dtype,
                 kind: str = "adam", betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adam_w_mode: bool = True,
                 bias_correction: bool = True, gradient_clipping: float = 0.0,
                 fp16_enabled: bool = False,
                 loss_scaler: Optional[DynamicLossScaler] = None,
                 scaler_state: Optional[LossScaleState] = None,
                 nvme_path: Optional[str] = None,
                 nvme_param_path: Optional[str] = None,
                 aio_config: Optional[dict] = None,
                 mesh=None, qat_fn=None):
        if not (segments and segments[0].kind == "first" \
            and segments[-1].kind == "last"):
            raise AssertionError("segments must run first → mid* → last")
        self.segments = segments
        self.compute_dtype = compute_dtype
        self.kind = kind
        self.gradient_clipping = gradient_clipping
        self.fp16_enabled = fp16_enabled
        self.loss_scaler = loss_scaler
        self.scaler_state = scaler_state
        self.mesh = mesh
        # QAT under offload: ``qat_fn(key, subtree, step) -> subtree`` applied to
        # every pushed key. Straight-through-estimator semantics come for free:
        # the VJP differentiates w.r.t. the QUANTIZED pushed values and the host
        # Adam applies those grads to the fp32 masters — exactly STE (the
        # resident engine quantizes inside the loss fn for the same effect).
        self.qat_fn = qat_fn
        self.push_step = 0           # host mirror of global step for QAT gating
        self._skipped_steps = 0
        self._fwd_fns: Dict[int, Any] = {}
        self._bwd_fns: Dict[int, Any] = {}
        self._loss_fns: Dict[int, Any] = {}
        self.nvme_params = nvme_param_path is not None
        # multi-process: per-process partitioned masters along the gradient layout
        # (the r3 optimizer-tier recipe — reference per-rank cpu offload,
        # stage_1_and_2.py:130); each process owns only its devices' unique shards
        self._partitioned = jax.process_count() > 1
        if self._partitioned and mesh is None:
            raise ValueError("multi-process offload_param needs a device mesh")
        import os
        if self.nvme_params:
            if kind not in ("adam", "adamw"):
                raise ValueError("offload_param.device='nvme' supports adam/adamw "
                                 f"only (got {kind!r})")
            if self._partitioned:
                # per-process partition files; nvme_param_path may be shared storage
                nvme_param_path = os.path.join(nvme_param_path,
                                               f"proc{jax.process_index()}")
            if nvme_path is None:
                # masters on disk imply the moment store on disk: if 4N of params
                # don't fit in host RAM, 8N of Adam moments certainly don't
                nvme_path = os.path.join(nvme_param_path, "moments")
        if nvme_path is not None and self._partitioned \
                and not nvme_path.endswith(f"proc{jax.process_index()}"):
            # per-process moment files regardless of which knob enabled the store
            # (slot sizes differ per process; a shared dir would cross-clobber)
            nvme_path = os.path.join(nvme_path, f"proc{jax.process_index()}")

        # ---- metadata pass (no compute): shapes / treedefs / leaf order ---------
        self.key_treedef: Dict[str, Any] = {}
        self.key_shapes: Dict[str, List[tuple]] = {}
        self._key_order: List[str] = []
        for si, seg in enumerate(segments):
            if not seg.init_keys:
                continue
            seg_rng = jax.random.fold_in(rng, si)
            abstract = jax.eval_shape(seg.init_fn, seg_rng)
            if not (len(abstract) == len(seg.init_keys)):
                raise AssertionError(f"segment {seg.name}: init_fn must return one subtree per init_key")
            for key, subtree in zip(seg.init_keys, abstract):
                if not (key not in self.key_treedef):
                    raise AssertionError(f"segment {seg.name}: key {key!r} initialised twice")
                leaves, treedef = jax.tree_util.tree_flatten(subtree)
                self.key_treedef[key] = treedef
                self.key_shapes[key] = [tuple(l.shape) for l in leaves]
                self._key_order.append(key)
        # global flat leaf order (checkpoints, optimizer state, NVMe files)
        self._leaf_index: Dict[str, List[int]] = {}
        sizes: List[int] = []
        for k in self._key_order:
            idx = []
            for shape in self.key_shapes[k]:
                idx.append(len(sizes))
                sizes.append(int(np.prod(shape)))
            self._leaf_index[k] = idx
        self.leaf_sizes = sizes
        self.total_params = int(sum(sizes))

        # ---- partitioned-mode slot bookkeeping ----------------------------------
        # One SLOT = one unique addressable shard of one leaf in the GRADIENT
        # layout (dim-0 sharded over the dp axes when divisible, else replicated).
        # Masters/accumulators/NVMe files index by slot; replicated leaves are
        # updated identically on every process but counted toward the grad norm by
        # their lowest-device owner only.
        if self._partitioned:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ...parallel.mesh import AXIS_DATA, AXIS_FSDP
            dp_axes = tuple(ax for ax in (AXIS_DATA, AXIS_FSDP)
                            if self.mesh.size(ax) > 1)
            dp_total = int(np.prod([self.mesh.size(ax) for ax in dp_axes])) \
                if dp_axes else 1

            def gspec(shape):
                if dp_axes and shape and shape[0] % dp_total == 0:
                    return P(dp_axes, *([None] * (len(shape) - 1)))
                return P(*([None] * len(shape)))

            my_proc = jax.process_index()
            self._gshard: Dict[str, List[Any]] = {}
            self._slot_meta: List[tuple] = []   # (key, li, norm_key, shape, owned)
            self._slots_by_leaf: Dict[tuple, List[int]] = {}
            slot_sizes: List[int] = []
            for k in self._key_order:
                shards = []
                for li, shape in enumerate(self.key_shapes[k]):
                    sh = NamedSharding(self.mesh.mesh, gspec(shape))
                    shards.append(sh)
                    # ownership: the process of the lowest-id device holding each
                    # distinct shard (deterministic, no communication)
                    owner: Dict[tuple, Any] = {}
                    local: Dict[tuple, tuple] = {}
                    from .offload import _norm_index
                    for dev, index in sh.devices_indices_map(shape).items():
                        nk = _norm_index(index, shape)
                        if nk not in owner or dev.id < owner[nk].id:
                            owner[nk] = dev
                        if dev.process_index == my_proc:
                            local[nk] = tuple(b - a for a, b in nk)
                    ids = []
                    for nk in sorted(local):
                        ids.append(len(self._slot_meta))
                        slot_sizes.append(int(np.prod(local[nk])) if local[nk]
                                          else 1)
                        self._slot_meta.append(
                            (k, li, nk, local[nk],
                             owner[nk].process_index == my_proc))
                    self._slots_by_leaf[(k, li)] = ids
                self._gshard[k] = shards
            self._flat_sizes = slot_sizes
        else:
            self._flat_sizes = sizes

        self.param_tier = (_NVMeParamTier(nvme_param_path, self._flat_sizes,
                                          aio_config or {})
                           if self.nvme_params else None)

        # ---- init pass: one segment at a time (no full-model device or host
        # materialisation — NVMe mode writes each key to disk and frees it;
        # partitioned mode inits straight into the grad layout and keeps only this
        # process's unique shards) ------------------------------------------------
        self.masters: Optional[Dict[str, List[np.ndarray]]] = \
            None if (self.nvme_params or self._partitioned) else {}
        self._masters_p: Optional[List[np.ndarray]] = \
            [None] * len(self._flat_sizes) \
            if (self._partitioned and not self.nvme_params) else None
        init_jits: Dict[Any, Any] = {}   # one jit per shared init_fn object
        for si, seg in enumerate(segments):
            if not seg.init_keys:
                continue
            seg_rng = jax.random.fold_in(rng, si)
            if seg.init_fn not in init_jits:
                if self._partitioned:
                    out_sh = tuple(
                        jax.tree_util.tree_unflatten(self.key_treedef[k],
                                                     self._gshard[k])
                        for k in seg.init_keys)
                    init_jits[seg.init_fn] = jax.jit(
                        lambda r, fn=seg.init_fn: jax.tree_util.tree_map(
                            lambda x: x.astype(jnp.float32), fn(r)),
                        out_shardings=out_sh)
                else:
                    init_jits[seg.init_fn] = jax.jit(seg.init_fn)
            dev = init_jits[seg.init_fn](seg_rng)   # device, segment-sized tuple
            for key, subtree in zip(seg.init_keys, dev):
                leaves = jax.tree_util.tree_leaves(subtree)
                for l in leaves:
                    l.copy_to_host_async()
                if self._partitioned:
                    from .offload import unique_local_shards
                    for li, l in enumerate(leaves):
                        pairs = unique_local_shards(l)
                        ids = self._slots_by_leaf[(key, li)]
                        if not ([p[0] for p in pairs] == \
                            [self._slot_meta[s][2] for s in ids]):
                            raise AssertionError(
                                "device sharding drifted from the masters "
                                "partition")
                        for sid, (_, data) in zip(ids, pairs):
                            flat = np.array(data, dtype=np.float32,
                                            copy=True).reshape(-1)
                            if self.nvme_params:
                                self.param_tier.write_master(sid, flat)
                            else:
                                self._masters_p[sid] = flat
                elif self.nvme_params:
                    for i, l in zip(self._leaf_index[key], leaves):
                        self.param_tier.write_master(
                            i, np.asarray(l, dtype=np.float32).reshape(-1))
                else:
                    self.masters[key] = [
                        np.array(l, dtype=np.float32, copy=True).reshape(-1)
                        for l in leaves]
            del dev

        self._accum: Optional[Dict[str, List[np.ndarray]]] = None
        self._accum_p: Optional[List[np.ndarray]] = None
        if self._partitioned and not self.nvme_params:
            self._accum_p = [np.zeros(s, np.float32) for s in self._flat_sizes]
        elif not self.nvme_params:
            self._accum = {k: [np.zeros_like(m) for m in self.masters[k]]
                           for k in self._key_order}

        self.nvme = None
        if kind in ("adam", "adamw"):
            if nvme_path is not None:
                from .offload import _NVMeMomentStore
                self.nvme = _NVMeMomentStore(nvme_path, self._flat_sizes,
                                             aio_config or {})
                self._adam_kwargs = dict(betas=betas, eps=eps,
                                         weight_decay=weight_decay,
                                         adam_w_mode=adam_w_mode,
                                         bias_correction=bias_correction)
                self.step_count = 0
            else:
                self.opt = DeepSpeedCPUAdam(self._flat_masters(), betas=betas,
                                            eps=eps, weight_decay=weight_decay,
                                            adamw_mode=adam_w_mode,
                                            bias_correction=bias_correction)
                # masters already flat fp32 → shared views, updates land in self.masters
                self._rebind_masters(self.opt.params)
        elif kind == "adagrad":
            self.eps, self.weight_decay = eps, weight_decay
            self.sq_sum = [np.zeros(s, np.float32) for s in self._flat_sizes]
            self.step_count = 0
        else:
            raise ValueError(f"offload_param optimizer kind {kind!r} "
                             "(adam/adamw/adagrad)")
        self.cache = _StreamCache(self._push_segment)
        log_dist(
            f"ZeRO-3 param offload: {self.total_params:,} params on "
            f"{'NVMe' if self.nvme_params else 'host'} across "
            f"{len(segments)} segments "
            f"({'native SIMD' if native_available() else 'numpy fallback'} {kind}"
            f"{', nvme moments' if self.nvme is not None else ''})", ranks=[0])

    def _rebind_masters(self, flat: List[np.ndarray]):
        """Re-point the masters at (possibly re-allocated) flat buffers."""
        if self._partitioned:
            self._masters_p = list(flat)
            return
        i = 0
        for k in self._key_order:
            n = len(self.masters[k])
            self.masters[k] = list(flat[i:i + n])
            i += n

    def _flat_masters(self) -> List[np.ndarray]:
        if self._partitioned:
            return self._masters_p
        return [m for k in self._key_order for m in self.masters[k]]

    def _flat_accum(self) -> List[np.ndarray]:
        if self._partitioned:
            return self._accum_p
        return [g for k in self._key_order for g in self._accum[k]]

    # ------------------------------------------------------------------ device push
    def _replicated_sharding(self):
        if self.mesh is not None:
            return self.mesh.replicated()
        return None

    def _push_key(self, key: str):
        tree, nbytes = self._push_key_raw(key)
        if self.qat_fn is not None:
            tree = self.qat_fn(key, tree, self.push_step)
        return tree, nbytes

    def _push_key_raw(self, key: str):
        if self._partitioned:
            return self._push_key_partitioned(key)
        from .offload import cast_master_to
        sh = self._replicated_sharding()
        outs, nbytes = [], 0
        if self.nvme_params:
            flats = self.param_tier.read_masters_pipelined(self._leaf_index[key])
        else:
            flats = self.masters[key]
        for m, shape in zip(flats, self.key_shapes[key]):
            host = cast_master_to(m, shape, self.compute_dtype)
            nbytes += host.nbytes
            outs.append(jax.device_put(host, sh) if sh is not None
                        else jax.device_put(host))
        return jax.tree_util.tree_unflatten(self.key_treedef[key], outs), nbytes

    def _push_key_partitioned(self, key: str):
        """Per-process master slices → grad-layout global arrays → one jitted
        reshard to replicated (XLA all-gathers over ICI — the optimizer tier's
        ``_push_partitioned`` applied per streamed key)."""
        from .offload import _norm_index, cast_master_to
        outs, nbytes = [], 0
        slot_ids = [sid for li in range(len(self.key_shapes[key]))
                    for sid in self._slots_by_leaf[(key, li)]]
        if self.nvme_params:
            slot_data = dict(zip(slot_ids, (
                f.copy() for f in
                self.param_tier.read_masters_pipelined(slot_ids))))
        else:
            slot_data = {sid: self._masters_p[sid] for sid in slot_ids}
        for li, shape in enumerate(self.key_shapes[key]):
            gsh = self._gshard[key][li]
            by_idx = {self._slot_meta[sid][2]: sid
                      for sid in self._slots_by_leaf[(key, li)]}
            cast_cache: Dict[int, np.ndarray] = {}   # slot → cast host array
            singles = []
            for dev, index in gsh.addressable_devices_indices_map(shape).items():
                nk = _norm_index(index, shape)
                sid = by_idx[nk]
                if sid not in cast_cache:
                    cast_cache[sid] = cast_master_to(
                        slot_data[sid], self._slot_meta[sid][3],
                        self.compute_dtype)
                    nbytes += cast_cache[sid].nbytes
                singles.append(jax.device_put(cast_cache[sid], dev))
            outs.append(jax.make_array_from_single_device_arrays(
                shape, gsh, singles))
        tree = jax.tree_util.tree_unflatten(self.key_treedef[key], outs)
        if not hasattr(self, "_reshard_fns"):
            self._reshard_fns = {}
        if key not in self._reshard_fns:
            repl = self._replicated_sharding()
            self._reshard_fns[key] = jax.jit(
                lambda t: t, out_shardings=jax.tree_util.tree_map(
                    lambda _: repl, tree))
        return self._reshard_fns[key](tree), nbytes

    def _push_segment(self, si: int):
        """Ordered tuple of subtrees (param_keys order) — uniform pytree structure
        across equally-shaped segments, so they share jit entries."""
        trees, total = [], 0
        for key in self.segments[si].param_keys:
            tree, nbytes = self._push_key(key)
            trees.append(tree)
            total += nbytes
        return tuple(trees), total

    # ------------------------------------------------------------------ jitted fns
    # caches key on (kind, apply_fn object): segments sharing an apply_fn (uniform
    # layer groups) share ONE jit wrapper, hence one compilation per arg structure
    def _fwd(self, si: int):
        seg = self.segments[si]
        key = (seg.kind, seg.apply_fn)
        if key not in self._fwd_fns:
            self._fwd_fns[key] = jax.jit(seg.apply_fn)
        return self._fwd_fns[key]

    def _bwd(self, si: int):
        """Per-segment VJP. Recomputes the segment forward inside (remat at segment
        granularity); parameter cotangents come back replicated fp32 (partitioned
        mode: in the grad layout, so each process D2H-reads only its own shards)."""
        seg = self.segments[si]
        key = (seg.kind, seg.apply_fn)
        if key in self._bwd_fns:
            return self._bwd_fns[key]
        # param cotangents come back replicated (one addressable full copy for the host
        # read); activation cotangents stay wherever XLA wants them
        repl = self._replicated_sharding()
        if self._partitioned:
            repl = tuple(jax.tree_util.tree_unflatten(self.key_treedef[k],
                                                      self._gshard[k])
                         for k in seg.param_keys)
        if seg.kind == "first":
            def bwd(p, batch, rng, gout):
                _, vjp = jax.vjp(lambda pp: seg.apply_fn(pp, batch, rng), p)
                (gp,) = vjp(gout)
                return jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), gp)
            out_shardings = None if repl is None else repl
            self._bwd_fns[key] = jax.jit(bwd, out_shardings=out_shardings)
        elif seg.kind == "mid":
            def bwd(p, x, batch, rng, gout):
                _, vjp = jax.vjp(
                    lambda pp, xx: seg.apply_fn(pp, xx, batch, rng), p, x)
                gp, gx = vjp(gout)
                gp = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), gp)
                return gp, gx
            out_shardings = None if repl is None else (repl, None)
            self._bwd_fns[key] = jax.jit(bwd, out_shardings=out_shardings)
        else:
            def bwd(p, x, batch, rng, scale):
                loss, vjp = jax.vjp(
                    lambda pp, xx: seg.apply_fn(pp, xx, batch, rng), p, x)
                gp, gx = vjp(scale.astype(loss.dtype))
                gp = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), gp)
                return loss, gp, gx
            out_shardings = None if repl is None else (None, repl, None)
            self._bwd_fns[key] = jax.jit(bwd, out_shardings=out_shardings)
        return self._bwd_fns[key]

    def _loss_only(self, si: int):
        seg = self.segments[si]
        fkey = (seg.kind, seg.apply_fn)
        if fkey not in self._loss_fns:
            self._loss_fns[fkey] = jax.jit(seg.apply_fn)
        return self._loss_fns[fkey]

    # ------------------------------------------------------------------ accumulation
    def _zero_accum(self):
        if self.nvme_params:
            self.param_tier.reset_grads()
            return
        if self._partitioned:
            for g in self._accum_p:
                g.fill(0.0)
            return
        for k in self._key_order:
            for g in self._accum[k]:
                g.fill(0.0)

    def _accumulate(self, si: int, gp):
        """Fold one segment's device param-grads (tuple, param_keys order) into the host
        fp32 accumulators (NVMe mode: read-modify-write of the on-disk accumulator
        files). The caller dispatches the NEXT segment's backward before invoking
        this, so the blocking D2H read below overlaps that segment's compute."""
        for key, sub in zip(self.segments[si].param_keys, gp):
            leaves = jax.tree_util.tree_leaves(sub)
            for l in leaves:
                l.copy_to_host_async()
            if self._partitioned:
                from .offload import unique_local_shards
                for li, l in enumerate(leaves):
                    pairs = unique_local_shards(l)
                    ids = self._slots_by_leaf[(key, li)]
                    if not ([p[0] for p in pairs] == \
                        [self._slot_meta[s][2] for s in ids]):
                        raise AssertionError("gradient sharding drifted from the masters partition")
                    for sid, (_, data) in zip(ids, pairs):
                        flat = np.asarray(data, dtype=np.float32).reshape(-1)
                        if self.nvme_params:
                            self.param_tier.accumulate_leaf(sid, flat)
                        else:
                            self._accum_p[sid] += flat
            elif self.nvme_params:
                for i, l in zip(self._leaf_index[key], leaves):
                    self.param_tier.accumulate_leaf(
                        i, np.asarray(l, dtype=np.float32).reshape(-1))
            else:
                for acc, l in zip(self._accum[key], leaves):
                    acc += np.asarray(l, dtype=np.float32).reshape(-1)

    # ------------------------------------------------------------------ step
    def _cur_scale(self) -> float:
        if self.scaler_state is None:
            return 1.0
        return float(self.scaler_state.cur_scale)

    def train_step(self, microbatches: List[Any], lr: float, rng) -> Dict[str, Any]:
        """One optimizer step over ``len(microbatches)`` streamed fwd+bwd passes.

        ``microbatches``: list of already-globalized device batches (the engine's
        ``_globalize`` output). Returns the engine's metrics dict."""
        G = len(self.segments)
        n_micro = len(microbatches)
        scale = self._cur_scale()
        scale_dev = jnp.float32(scale)
        self._zero_accum()
        losses = []
        cache = self.cache
        pending = None  # (si, gp) whose D2H accumulation is deferred one segment

        for mi, batch in enumerate(microbatches):
            mb_rng = jax.random.fold_in(rng, mi)
            # ---- forward stream: segments 0..G-2 (last is fused into its VJP) ----
            xs: List[Any] = [None] * G   # xs[g] = input carry of segment g (g >= 1)
            x = None
            for g in range(G - 1):
                if g + 1 < G:
                    cache.prefetch(g + 1)
                p = cache.get(g)
                seg_rng = jax.random.fold_in(mb_rng, g)
                if self.segments[g].kind == "first":
                    x = self._fwd(g)(p, batch, seg_rng)
                else:
                    xs[g] = x
                    x = self._fwd(g)(p, x, batch, seg_rng)
                if g < G - 2:
                    cache.evict(g)
            xs[G - 1] = x

            # ---- backward stream: G-1 .. 0 --------------------------------------
            gout = None
            for g in range(G - 1, -1, -1):
                if g - 1 >= 0:
                    cache.prefetch(g - 1)
                p = cache.get(g)
                seg_rng = jax.random.fold_in(mb_rng, g)
                seg = self.segments[g]
                if seg.kind == "last":
                    loss, gp, gout = self._bwd(g)(p, xs[g], batch, seg_rng,
                                                  scale_dev)
                    losses.append(loss)
                elif seg.kind == "mid":
                    gp, gout = self._bwd(g)(p, xs[g], batch, seg_rng, gout)
                else:
                    gp = self._bwd(g)(p, batch, seg_rng, gout)
                    gout = None
                xs[g] = None
                if g > 0:
                    cache.evict(g)   # segment 0 stays warm for the next microbatch's
                                     # forward (params only change at the host update)
                if pending is not None:
                    self._accumulate(*pending)   # overlaps this segment's compute
                pending = (g, gp)
            if pending is not None:
                self._accumulate(*pending)
                pending = None
        cache.clear()

        # ---- host update ---------------------------------------------------------
        metrics = self._host_update(lr, n_micro, scale)
        metrics["loss"] = float(np.mean([float(l) for l in losses]))
        self.push_step += 1
        return metrics

    def _owned_flags(self) -> List[bool]:
        """Which flat slots this process counts toward the GLOBAL grad norm:
        everything in single-process mode; in partitioned mode only slots whose
        lowest-id device lives here (replicated slots exist on every process but
        must be counted once)."""
        if self._partitioned:
            return [m[4] for m in self._slot_meta]
        return [True] * len(self._flat_sizes)

    def _global_sq(self, owned_sq: float) -> float:
        """Cross-process sum of the owned sum-of-squares (grad-norm all-reduce —
        reference ``get_global_norm_of_tensors`` across dp ranks)."""
        if not self._partitioned:
            return owned_sq
        from jax.experimental import multihost_utils
        return float(np.asarray(multihost_utils.process_allgather(
            np.float64(owned_sq))).sum())

    # shared overflow/clip/scaler scaffolding — ONE definition so the RAM and NVMe
    # update paths cannot silently diverge (test_matches_ram_mode pins them equal)
    def _norm_overflow(self, total_sq: float):
        norm = float(np.sqrt(total_sq))
        return norm, self.fp16_enabled and not np.isfinite(norm)

    def _clip_coef(self, norm: float) -> float:
        clip = self.gradient_clipping
        if clip and clip > 0 and np.isfinite(norm) and norm > clip:
            return clip / (norm + 1e-6)
        return 1.0

    def _finish_update(self, overflow: bool, norm: float, scale: float
                       ) -> Dict[str, Any]:
        if overflow:
            self._skipped_steps += 1
        if self.loss_scaler is not None and self.scaler_state is not None:
            self.scaler_state = self.loss_scaler.update(
                self.scaler_state, jnp.asarray(overflow))
        return {"grad_norm": norm, "overflow": overflow, "loss_scale": scale}

    def _host_update(self, lr: float, n_micro: int, scale: float) -> Dict[str, Any]:
        if self.nvme_params:
            return self._nvme_params_update(lr, n_micro, scale)
        inv = np.float32(1.0 / (scale * n_micro))
        total_sq = 0.0
        flat_grads = self._flat_accum()
        owned = self._owned_flags()
        for j, g in enumerate(flat_grads):
            g *= inv
            if owned[j]:
                total_sq += float(np.dot(g, g))
        norm, overflow = self._norm_overflow(self._global_sq(total_sq))
        coef = self._clip_coef(norm)
        if coef != 1.0:
            coef = np.float32(coef)
            for g in flat_grads:
                g *= coef
        if not overflow:
            masters = self._flat_masters()
            if self.nvme is not None:
                self.step_count += 1
                self.nvme.adam_step_all(masters, flat_grads, lr, self.step_count,
                                        **self._adam_kwargs)
            elif self.kind in ("adam", "adamw"):
                self.opt.step(flat_grads, lr=lr)
            else:
                self.step_count += 1
                for p, s, g in zip(masters, self.sq_sum, flat_grads):
                    adagrad_step(p, s, g, lr, self.eps, self.weight_decay)
        return self._finish_update(overflow, norm, scale)

    def _nvme_params_update(self, lr: float, n_micro: int, scale: float
                            ) -> Dict[str, Any]:
        """Streamed masters+grads+moments update: while leaf ``i`` runs the SIMD
        Adam, leaf ``i+1``'s three tensors stream in from disk and leaf ``i-1``'s
        masters/moments stream back out (reference
        ``pipelined_optimizer_swapper.py:55`` read/compute/write overlap). The
        global grad norm comes free from the per-leaf sums-of-squares tracked at
        accumulation time — no extra pass over the grad files."""
        from ...ops.adam.cpu_adam import adam_step
        tier, mom = self.param_tier, self.nvme
        inv = 1.0 / (scale * n_micro)
        owned_sq = float(sum(sq for sq, o in zip(tier.leaf_sq,
                                                 self._owned_flags()) if o))
        norm, overflow = self._norm_overflow(self._global_sq(owned_sq) * inv * inv)
        coef = np.float32(inv * self._clip_coef(norm))
        if not overflow:
            self.step_count += 1
            kw = self._adam_kwargs
            n = len(self._flat_sizes)
            tier.fetch_mg(0, 0)
            mom.fetch_slot(0, 0)
            tier.handle.wait()
            mom.wait()
            for i in range(n):
                if i + 1 < n:  # overlap: next leaf streams in during this compute
                    tier.fetch_mg(i + 1, (i + 1) % 2)
                    mom.fetch_slot(i + 1, (i + 1) % 2)
                s = self._flat_sizes[i]
                g = tier._gbuf[i % 2][:s]
                g *= coef
                m_mom, v_mom = mom.slot_views(i, i % 2)
                adam_step(tier._mbuf[i % 2][:s], m_mom, v_mom, g, lr,
                          kw["betas"][0], kw["betas"][1], kw["eps"],
                          kw["weight_decay"], kw["adam_w_mode"], self.step_count,
                          kw["bias_correction"])
                tier.write_master_async(i, i % 2)
                mom.write_slot(i, i % 2)
                tier.handle.wait()
                mom.wait()
        return self._finish_update(overflow, norm, scale)

    # ------------------------------------------------------------------ eval
    def eval_loss(self, batch, rng) -> Any:
        G = len(self.segments)
        cache = self.cache
        x = None
        for g in range(G):
            if g + 1 < G:
                cache.prefetch(g + 1)
            p = cache.get(g)
            seg_rng = jax.random.fold_in(rng, g)
            seg = self.segments[g]
            if seg.kind == "first":
                x = self._fwd(g)(p, batch, seg_rng)
            elif seg.kind == "mid":
                x = self._fwd(g)(p, x, batch, seg_rng)
            else:
                x = self._loss_only(g)(p, x, batch, seg_rng)
            cache.evict(g)
        cache.clear()
        return x

    # ------------------------------------------------------------------ test hooks
    def _master_flat(self, key: str, li: int) -> np.ndarray:
        """Leaf ``li`` of ``key``'s fp32 master, flat (copied out of NVMe scratch).
        Partitioned mode: assembled from this process's slots (replicated-layout
        leaves only — sharded leaves would need cross-process data; use the pushed
        device params for those)."""
        if self._partitioned:
            ids = self._slots_by_leaf[(key, li)]
            if len(ids) == 1 and self._slot_meta[ids[0]][3] == \
                    self.key_shapes[key][li]:
                sid = ids[0]
                if self.nvme_params:
                    return self.param_tier.read_master(sid).copy()
                return self._masters_p[sid]
            raise NotImplementedError(
                "full master assembly of dp-sharded leaves is per-process under "
                "multi-process offload — read the pushed device params instead")
        if self.nvme_params:
            return self.param_tier.read_master(self._leaf_index[key][li]).copy()
        return self.masters[key][li]

    def full_params_host(self) -> Dict[str, Any]:
        """Assemble the full fp32 parameter tree on host (tests / export only)."""
        return {k: jax.tree_util.tree_unflatten(
                    self.key_treedef[k],
                    [self._master_flat(k, li).reshape(s)
                     for li, s in enumerate(self.key_shapes[k])])
                for k in self.key_treedef}

    def load_full_params(self, tree: Dict[str, Any]):
        """Seed masters from a host parameter tree (same structure as
        ``full_params_host``); optimizer moments are left untouched."""
        for k in self._key_order:
            leaves = jax.tree_util.tree_leaves(tree[k])
            if not (len(leaves) == len(self.key_shapes[k])):
                raise AssertionError(f"leaf mismatch for {k!r}")
            if self._partitioned:
                for li, src in enumerate(leaves):
                    flat = np.asarray(src, dtype=np.float32).reshape(
                        self.key_shapes[k][li])
                    for sid in self._slots_by_leaf[(k, li)]:
                        nk = self._slot_meta[sid][2]
                        sl = flat[tuple(slice(a, b) for a, b in nk)].reshape(-1)
                        if self.nvme_params:
                            self.param_tier.write_master(sid, sl)
                        else:
                            np.copyto(self._masters_p[sid], sl)
            elif self.nvme_params:
                for i, src in zip(self._leaf_index[k], leaves):
                    self.param_tier.write_master(
                        i, np.asarray(src, dtype=np.float32).reshape(-1))
            else:
                for dst, src in zip(self.masters[k], leaves):
                    np.copyto(dst, np.asarray(src, dtype=np.float32).reshape(-1))

    @property
    def skipped_steps(self) -> int:
        return self._skipped_steps

    # ------------------------------------------------------------------ checkpoint
    def _light_state_dict(self) -> Dict[str, Any]:
        """Masters + step + scaler — everything EXCEPT the Adam moments. The NVMe
        checkpoint path uses this so the on-disk moment store is never materialised in
        host RAM (the tier exists because 2× fp32 moments don't fit there). With
        masters themselves on NVMe they are excluded too (streamed by file copy)."""
        sd: Dict[str, Any] = {"step": np.asarray(getattr(self, "step_count", 0), dtype=np.int64)}
        if not self.nvme_params and not self._partitioned:
            for k in self._key_order:
                for li, (m, s) in enumerate(zip(self.masters[k],
                                                self.key_shapes[k])):
                    sd[f"master/{k}/{li}"] = m.reshape(s)
        if self.scaler_state is not None:
            sd["scaler"] = np.asarray(
                [float(self.scaler_state.cur_scale),
                 float(self.scaler_state.cur_hysteresis),
                 float(self.scaler_state.last_overflow_iter),
                 float(self.scaler_state.iteration)], np.float64)
        return sd

    def _no_partitioned_state_dict(self):
        if self._partitioned:
            raise NotImplementedError(
                "partitioned (multi-process) offload_param checkpoints through "
                "per-rank partition files — use save_to/load_from (the engine's "
                "save_checkpoint/load_checkpoint do), not state_dict")

    def state_dict(self) -> dict:
        """Full state incl. moments in host RAM — RAM-mode checkpoints and tests.
        NVMe mode materialises the moment store; use save_to for streaming."""
        self._no_partitioned_state_dict()
        sd = self._light_state_dict()
        if self.nvme_params:
            for k in self._key_order:
                for li, s in enumerate(self.key_shapes[k]):
                    sd[f"master/{k}/{li}"] = self._master_flat(k, li).reshape(s)
        if self.nvme is not None:
            ms, vs = self.nvme.read_moments()
            for i, (m, v) in enumerate(zip(ms, vs)):
                sd[f"m/{i}"], sd[f"v/{i}"] = m, v
        elif self.kind in ("adam", "adamw"):
            opt_sd = self.opt.state_dict()
            sd["step"] = np.asarray(opt_sd["step"], dtype=np.int64)
            for i, (m, v) in enumerate(zip(opt_sd["m"], opt_sd["v"])):
                sd[f"m/{i}"], sd[f"v/{i}"] = m, v
        else:
            for i, s in enumerate(self.sq_sum):
                sd[f"sq_sum/{i}"] = s
        return sd

    def _restore_masters(self, sd: dict):
        self._no_partitioned_state_dict()
        for k in self._key_order:
            for li in range(len(self.key_shapes[k])):
                flat = np.asarray(sd[f"master/{k}/{li}"],
                                  dtype=np.float32).reshape(-1)
                if self.nvme_params:
                    self.param_tier.write_master(self._leaf_index[k][li], flat)
                else:
                    np.copyto(self.masters[k][li], flat)

    def _restore_scaler(self, sd: dict):
        if "scaler" in sd and self.scaler_state is not None:
            v = np.asarray(sd["scaler"])
            self.scaler_state = LossScaleState(
                cur_scale=jnp.float32(v[0]), cur_hysteresis=jnp.int32(v[1]),
                last_overflow_iter=jnp.int32(v[2]), iteration=jnp.int32(v[3]))

    def load_state_dict(self, sd: dict):
        self._no_partitioned_state_dict()
        self._restore_masters(sd)
        n = len(self._flat_sizes)
        if self.nvme is not None:
            self.step_count = int(sd["step"])
            self.nvme.write_moments([np.asarray(sd[f"m/{i}"]) for i in range(n)],
                                    [np.asarray(sd[f"v/{i}"]) for i in range(n)])
        elif self.kind in ("adam", "adamw"):
            self.opt.load_state_dict({
                "step": int(sd["step"]),
                "m": [np.asarray(sd[f"m/{i}"]) for i in range(n)],
                "v": [np.asarray(sd[f"v/{i}"]) for i in range(n)]})
        else:
            self.step_count = int(sd["step"])
            for i, s in enumerate(self.sq_sum):
                np.copyto(s, np.asarray(sd[f"sq_sum/{i}"],
                                        dtype=np.float32).reshape(-1))
        self._restore_scaler(sd)

    def _partition_meta(self) -> dict:
        """Self-describing layout of this rank's partition file: enables OFFLINE
        consolidation (``checkpoint.export.consolidate_partitioned_checkpoint``)
        without reconstructing the coordinator or its mesh."""
        return {
            "version": 1,
            "n_ranks": jax.process_count(),
            "rank": jax.process_index(),
            "kind": self.kind,
            "nvme_params": bool(self.nvme_params),
            "nvme_moments": self.nvme is not None,
            "slots": [
                {"key": k, "li": li,
                 "slice": [[int(a), int(b)] for a, b in nk],
                 "owned": bool(owned)}
                for (k, li, nk, _shape, owned) in self._slot_meta],
            "leaf_names": {k: _leaf_dotted_names(k, self.key_treedef[k])
                           for k in self._key_order},
            "leaf_shapes": {k: [list(s) for s in self.key_shapes[k]]
                            for k in self._key_order},
        }

    def save_to(self, checkpoint_engine, path: str):
        if self._partitioned:
            # one partition file per process (reference per-rank zero_pp_rank_*
            # files) — resume requires the topology that wrote it
            import json
            rank = jax.process_index()
            data = {f"master_{i}": m for i, m in
                    enumerate(self._masters_p or [])}
            data["meta_json"] = np.frombuffer(
                json.dumps(self._partition_meta()).encode(), np.uint8)
            data["step"] = np.asarray(getattr(self, "step_count", 0), dtype=np.int64)
            if self.scaler_state is not None:
                data["scaler"] = self._light_state_dict()["scaler"]
            if self.nvme_params:
                self.param_tier.copy_masters_to(path + f"_masters_p{rank}")
            if self.nvme is not None:
                self.nvme.copy_files_to(path + f"_moments_p{rank}")
            elif self.kind in ("adam", "adamw"):
                sd = self.opt.state_dict()
                data["step"] = np.asarray(sd["step"], dtype=np.int64)
                for i, (m, v) in enumerate(zip(sd["m"], sd["v"])):
                    data[f"m_{i}"], data[f"v_{i}"] = m, v
            else:
                for i, s in enumerate(self.sq_sum):
                    data[f"sq_{i}"] = s
            np.savez(path + f"_part{rank}.npz", **data)
            return
        if self.nvme is not None:
            # on-disk state (moments; with nvme_params also masters) is already
            # serialized — stream by file copy, never through host RAM
            checkpoint_engine.save(self._light_state_dict(), path)
            self.nvme.copy_files_to(path + "_moments")
            if self.nvme_params:
                self.param_tier.copy_masters_to(path + "_masters")
            return
        checkpoint_engine.save(self.state_dict(), path)

    def load_from(self, checkpoint_engine, path: str,
                  load_optimizer_states: bool = True):
        """Restore masters (always) and optimizer state/scaler (when
        ``load_optimizer_states`` — reference ``load_checkpoint`` honours the same
        flag for fine-tune-from-pretrain restarts)."""
        if self._partitioned:
            rank = jax.process_index()
            with np.load(path + f"_part{rank}.npz") as data:
                if self.nvme_params:
                    self.param_tier.copy_masters_from(path + f"_masters_p{rank}")
                else:
                    for i, m in enumerate(self._masters_p):
                        np.copyto(m, data[f"master_{i}"])
                if load_optimizer_states:
                    if self.nvme is not None:
                        self.step_count = int(data["step"])
                        self.nvme.copy_files_from(path + f"_moments_p{rank}")
                    elif self.kind in ("adam", "adamw"):
                        n = len(self._flat_sizes)
                        self.opt.load_state_dict({
                            "step": int(data["step"]),
                            "m": [data[f"m_{i}"] for i in range(n)],
                            "v": [data[f"v_{i}"] for i in range(n)]})
                    else:
                        self.step_count = int(data["step"])
                        for i, s in enumerate(self.sq_sum):
                            np.copyto(s, data[f"sq_{i}"])
                    if "scaler" in data:
                        self._restore_scaler({"scaler": data["scaler"]})
            return
        if self.nvme is not None:
            sd = checkpoint_engine.load(path, template=self._light_state_dict())
            if self.nvme_params:
                self.param_tier.copy_masters_from(path + "_masters")
            else:
                self._restore_masters(sd)
            if load_optimizer_states:
                self.step_count = int(sd["step"])
                self.nvme.copy_files_from(path + "_moments")
                self._restore_scaler(sd)
            return
        sd = checkpoint_engine.load(path, template=self.state_dict())
        if load_optimizer_states:
            self.load_state_dict(sd)
        else:
            self._restore_masters(sd)
