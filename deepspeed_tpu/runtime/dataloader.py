"""Data loading.

Behavioural equivalent of reference ``deepspeed/runtime/dataloader.py``
(``DeepSpeedDataLoader:39``, ``RepeatingLoader:16``). Each JAX *process* loads its slice of the
global batch (rank-sharded sampling, the DistributedSampler role); the engine assembles the
process-local arrays into globally-sharded ``jax.Array``s via
``make_array_from_process_local_data``.
"""

import math
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np


class RepeatingLoader:
    """Reference ``dataloader.py:16`` — wrap an iterator to restart on StopIteration."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    def __len__(self):
        return len(self.loader)


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {k: _default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate([s[i] for s in samples])
                           for i in range(len(first)))
    arr = np.stack([np.asarray(s) for s in samples])
    return arr


class DeepSpeedDataLoader:
    """Rank-aware micro-batch loader over an indexable or iterable dataset.

    Yields process-local batches of shape ``(local_micro_batch, ...)`` where
    ``local_micro_batch = micro_batch_per_device * local_dp_devices``. With torch installed, a
    ``torch.utils.data.DataLoader`` may be passed straight through to the engine instead.
    """

    def __init__(self, dataset, batch_size: int, num_replicas: int = 1, rank: int = 0,
                 collate_fn: Optional[Callable] = None, shuffle: bool = False, seed: int = 0,
                 drop_last: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.drop_last = drop_last
        self._indexable = hasattr(dataset, "__getitem__") and hasattr(dataset, "__len__")

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        if not self._indexable:
            raise TypeError("length of an iterable dataset is unknown")
        per_replica = len(self.dataset) // self.num_replicas
        n = per_replica // self.batch_size
        if not self.drop_last and per_replica % self.batch_size:
            n += 1
        return n

    def __iter__(self) -> Iterator[Any]:
        if self._indexable:
            n = len(self.dataset)
            order = np.arange(n)
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self.epoch)
                rng.shuffle(order)
            # contiguous rank shard, like DistributedSampler without padding
            per = n // self.num_replicas
            order = order[self.rank * per:(self.rank + 1) * per]
            for i in range(0, len(order), self.batch_size):
                idx = order[i:i + self.batch_size]
                if self.drop_last and len(idx) < self.batch_size:
                    break
                yield self.collate_fn([self.dataset[int(j)] for j in idx])
        else:
            buf = []
            for item_i, sample in enumerate(self.dataset):
                if item_i % self.num_replicas != self.rank:
                    continue
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []
            if buf and not self.drop_last:
                yield self.collate_fn(buf)
