"""Training engine.

TPU-native re-design of reference ``deepspeed/runtime/engine.py`` (``DeepSpeedEngine:190``).
Where the reference wraps an eager nn.Module with autograd hooks, streams, and flat buffers,
this engine compiles ONE train step under ``jax.jit`` over a named device mesh:

- microbatch gradient accumulation is a ``lax.scan`` inside the step (reference: the
  forward/backward/step loop with ``is_gradient_accumulation_boundary``);
- ZeRO stages are sharding specs on the state pytree (see ``runtime/zero/partition.py``) —
  XLA inserts and overlaps reduce-scatter/all-gather;
- fp16 dynamic loss scaling and overflow-skip run inside the step (reference
  ``fp16/loss_scaler.py`` + ``CheckOverflow``), as data-parallel-free device arithmetic;
- parameters are materialised *already sharded* by jitting ``init`` with output shardings —
  the equivalent of ``zero.Init`` (``zero/partition_parameters.py:539``) without intercepting
  constructors.

The eager-looking ``forward()/backward()/step()`` triple is preserved for source compatibility
with reference training loops; ``train_batch()`` is the fused fast path.
"""

import os
import signal
import threading
import time
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..config.config import DeepSpeedConfig
from ..models.base import Model
from ..ops.adagrad.cpu_adagrad import adagrad
from ..ops.adam.fused_adam import fused_adam
from ..ops.lamb.fused_lamb import fused_lamb
from ..ops.optimizer import Optimizer, from_optax
from ..parallel.mesh import (AXIS_DATA, MeshSpec, get_global_mesh,
                             set_global_mesh)
from ..observability import profiler as obs_profiler
from ..observability.metrics import record_events as obs_record_events
from ..observability.trace import CAT_TRAIN, get_tracer
from ..parallel.overlap import resolve_overlap_config, set_overlap_config
from ..utils.comms_logging import (collective_spans, record_collective,
                                   spans_overlap_ratio, spans_total_bytes)
from ..utils.fault_injection import fault_point
from ..utils.logging import log_dist, logger
from ..utils.nvtx import annotate
from ..utils.timer import (BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER,
                           SynchronizedWallClockTimer, ThroughputTimer, TRAIN_BATCH_TIMER)
from .checkpoint_engine.checkpoint_engine import (
    CheckpointCorruptionError, LATEST_FILE, find_latest_committed_tag,
    is_committed_tag, make_checkpoint_engine, validate_manifest,
    write_latest_pointer)
from .dataloader import DeepSpeedDataLoader, RepeatingLoader
from .fp16.loss_scaler import DynamicLossScaler, LossScaleState, create_loss_scaler
from .lr_schedules import get_lr_scheduler
from .utils import (clip_by_global_norm, count_parameters, global_norm, tree_cast,
                    tree_zeros_like)
from .zero.partition import (grad_accum_specs, optimizer_state_specs, param_specs,
                             to_shardings)


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    scaler: LossScaleState
    global_step: jnp.ndarray
    skipped_steps: jnp.ndarray


#: bf16 peak TFLOPS per chip by device kind (for modeled Train/mfu when
#: ``flops_profiler.peak_tflops`` is unset; unknown kinds — CPU hosts — skip
#: the mfu event rather than publish a made-up number)
_PEAK_TFLOPS_BY_KIND = {
    "tpu v4": 275.0,
    "tpu v5 lite": 197.0,
    "tpu v5e": 197.0,
    "tpu v5p": 459.0,
    "tpu v6e": 918.0,
}


def _batch_tokens(batch) -> int:
    """Modeled token count of one global batch: element count of the leading
    array leaf (the input ids for LM batches; labels/masks share the shape)."""
    try:
        leaves = jax.tree_util.tree_leaves(batch)
        return int(np.prod(np.shape(leaves[0]))) if leaves else 0
    except Exception:                                  # pragma: no cover
        return 0


class DeepSpeedEngine:
    """See module docstring. Public surface mirrors reference ``DeepSpeedEngine``."""

    def __init__(self, args=None, model: Optional[Model] = None, optimizer=None,
                 model_parameters=None, training_data=None, lr_scheduler=None, mpu=None,
                 collate_fn=None, config=None, dont_change_device: bool = False,
                 mesh_spec: Optional[MeshSpec] = None, seed: int = 42):
        if not (model is not None):
            raise AssertionError("deepspeed_tpu.initialize requires a Model")
        if not (isinstance(model, Model)):
            raise AssertionError("model must be deepspeed_tpu.models.Model (see models.base.from_flax)")
        dist.init_distributed()
        self.module = model
        self.collate_fn = collate_fn
        self.mpu = mpu
        self.args = args
        self._seed = seed

        # ---- config + mesh (reference _configure_with_arguments:990) ------------
        self._config = (config if isinstance(config, DeepSpeedConfig)
                        else DeepSpeedConfig(config))
        self.zero_stage = self._config.zero_config.stage
        self.mesh_spec = mesh_spec or MeshSpec.from_config(
            self._config.mesh, zero_stage=self.zero_stage)
        set_global_mesh(self.mesh_spec)
        self._config.resolve_batch_config(self.mesh_spec.dp_world_size)
        # comm-compute overlap: installed like the mesh so model traces this
        # engine initiates see its setting (chunked TP matmuls / MoE a2a
        # pipeline); the quantized DP grad sync is gated separately below
        self.comm_overlap = resolve_overlap_config(self._config.comm_overlap)
        set_overlap_config(self.comm_overlap)
        # this engine's own trace-time span snapshot (the module accumulator
        # is process-global; other engines' traces land in it too)
        self._comm_spans = {}

        # ---- precision policy ---------------------------------------------------
        if self._config.fp16.enabled:
            self.compute_dtype = jnp.float16
        elif self._config.bf16.enabled:
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32
        self.loss_scaler, scaler_state0 = create_loss_scaler(self._config.fp16)

        # ---- ZeRO-Offload gate (reference stage_1_and_2.py:130 cpu_offload) -----
        off_cfg = self._config.zero_config.offload_optimizer
        self.offload_enabled = bool(off_cfg is not None and
                                    off_cfg.device not in (None, "none"))
        self._offload_tier = None
        # multi-process runs use per-process partitioned masters (see
        # zero/offload.py OffloadOptimizerTier._partitioned) — no world-size gate
        # ---- ZeRO-3 parameter offload (reference partition_parameters.py:539,
        # partitioned_param_coordinator.py:239) — host-resident params streamed per
        # model segment; implies the optimizer tier (host masters own the state)
        op_cfg = self._config.zero_config.offload_param
        self.param_offload_enabled = bool(op_cfg is not None and
                                          op_cfg.device not in (None, "none"))
        self._param_offload = None
        if self.param_offload_enabled:
            if self.zero_stage != 3:
                raise ValueError("zero_optimization.offload_param requires stage 3 "
                                 f"(got stage {self.zero_stage})")
            if model.segments is None:
                raise ValueError(
                    "offload_param requires a segmented model (Model.segments — see "
                    "models.causal_lm.causal_lm_segments); this model has none")
            # multi-process runs partition masters per process along the gradient
            # layout (ParamOffloadCoordinator._partitioned) — no world-size gate
            self.offload_enabled = False  # coordinator owns the optimizer tier
        if self._config.sparse_gradients_enabled:
            logger.warning(
                "sparse_gradients is a no-op on TPU: XLA gradients (including "
                "embedding grads) are dense by construction; the flag is accepted "
                "for config compatibility only")

        # ---- quantized DP grad sync (needs the offload gates above) -------------
        self._quantized_dp = self._quantized_dp_regime()

        # ---- optimizer (reference _configure_optimizer:1261) --------------------
        self.optimizer = self._configure_optimizer(optimizer)
        self.lr_scheduler = self._configure_lr_scheduler(lr_scheduler)

        # ---- sharded state materialisation (zero.Init equivalent) ---------------
        self._build_state(scaler_state0, seed)

        # ---- data ----------------------------------------------------------------
        self.training_dataloader = self._configure_dataloader(training_data)

        # ---- observability -------------------------------------------------------
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self._config.steps_per_print)
        if model.flops_per_sample:
            self.tput_timer.flops_per_sample = model.flops_per_sample
        self.monitor = self._configure_monitor()
        self.checkpoint_engine = make_checkpoint_engine(self._config.checkpoint_config)
        self.curriculum_scheduler = self._configure_curriculum()
        pld_cfg = self._config.progressive_layer_drop
        self.progressive_layer_drop = None
        self._pld_in_loss = False
        if pld_cfg.get("enabled", False):
            from .progressive_layer_drop import ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=pld_cfg.get("theta", 0.5), gamma=pld_cfg.get("gamma", 0.001))
            # theta reaches the compiled step only if the model opts in by accepting
            # a pld_theta kwarg in its loss_fn (and applies layer_drop with it)
            import inspect
            self._pld_in_loss = "pld_theta" in inspect.signature(
                self.module.loss_fn).parameters
            if not self._pld_in_loss:
                logger.warning(
                    "progressive_layer_drop enabled but the model's loss_fn does "
                    "not accept pld_theta — theta is scheduled but layers are NOT "
                    "dropped (wrap blocks with "
                    "runtime.progressive_layer_drop.layer_drop and add the kwarg)")

        # ---- step bookkeeping ----------------------------------------------------
        self.micro_steps = 0
        self._host_steps = 0   # host mirror of state.global_step (see train_batch)
        self._grad_acc = None
        self._cached_grads = None
        self._cached_loss = None
        self._last_metrics: Dict[str, Any] = {}
        self._fns: Dict[str, Any] = {}

        n_params = (self._param_offload.total_params if self.param_offload_enabled
                    else count_parameters(self.state.params))
        log_dist(
            f"engine ready: model={model.name} params={n_params:,} "
            f"zero_stage={self.zero_stage} dtype={self.compute_dtype.__name__} "
            f"mesh={self.mesh_spec.axis_sizes} "
            f"batch={self.train_batch_size()}(micro={self.train_micro_batch_size_per_gpu()}"
            f"×gas={self.gradient_accumulation_steps()}×dp={self.mesh_spec.dp_world_size})",
            ranks=[0])

    # ------------------------------------------------------------------ config
    def _parse_optimizer_config(self) -> Dict[str, Any]:
        """Normalised optimizer hyperparams from the config block (shared by the in-graph
        and the host-offloaded paths); parsed once and cached."""
        cached = getattr(self, "_opt_cfg_cache", None)
        if cached is not None:
            return cached
        name = self._config.optimizer_name or "adam"
        params = dict(self._config.optimizer_params)
        self._base_lr = params.pop("lr", 1e-3)
        out = {
            "name": name,
            "betas": tuple(params.pop("betas", (0.9, 0.999))),
            "eps": params.pop("eps", 1e-10 if name == "adagrad" else 1e-8),
            "weight_decay": params.pop("weight_decay", 0.0),
            # torch-style flag accepted in reference adam params
            "adam_w_mode": params.pop("adam_w_mode", name == "adamw") or name == "adamw",
            "bias_correction": params.pop("bias_correction", True),
            "max_coeff": params.pop("max_coeff", 10.0),
            "min_coeff": params.pop("min_coeff", 0.01),
        }
        params.pop("torch_adam", None)
        out["extra"] = params  # optimizer-specific keys (freeze_step, ...)
        self._opt_cfg_cache = out
        return out

    def _configure_optimizer(self, optimizer) -> Optional[Optimizer]:
        if optimizer is not None:
            if self.offload_enabled or self.param_offload_enabled:
                raise ValueError(
                    "zero_optimization offload tiers require a config-declared "
                    "optimizer (adam/adamw/adagrad), not a user optimizer object")
            if isinstance(optimizer, Optimizer):
                return optimizer
            if hasattr(optimizer, "init") and hasattr(optimizer, "update"):
                return from_optax(optimizer)
            raise TypeError(f"Unsupported optimizer object: {optimizer!r}")
        oc = self._parse_optimizer_config()
        name = oc["name"]
        if self.offload_enabled or self.param_offload_enabled:
            if name not in ("adam", "adamw", "fusedadam", "adagrad"):
                raise ValueError(f"offload tiers support adam/adamw/adagrad, "
                                 f"got {name!r}")
            return None  # host tier built in _build_state; no in-graph opt state
        if name in ("adam", "adamw", "fusedadam"):
            return fused_adam(betas=oc["betas"], eps=oc["eps"],
                              weight_decay=oc["weight_decay"],
                              adam_w_mode=oc["adam_w_mode"],
                              bias_correction=oc["bias_correction"])
        if name in ("lamb", "fusedlamb"):
            return fused_lamb(betas=oc["betas"], eps=oc["eps"],
                              weight_decay=oc["weight_decay"],
                              max_coeff=oc["max_coeff"], min_coeff=oc["min_coeff"])
        if name in ("onebitadam", "zerooneadam", "onebitlamb"):
            from .fp16.onebit import onebit_adam, onebit_lamb, zero_one_adam
            extra = oc["extra"]
            if name == "onebitadam":
                return onebit_adam(betas=oc["betas"], eps=oc["eps"],
                                   weight_decay=oc["weight_decay"],
                                   freeze_step=extra.get("freeze_step", 100),
                                   adam_w_mode=oc["adam_w_mode"])
            if name == "onebitlamb":
                return onebit_lamb(betas=oc["betas"], eps=oc["eps"],
                                   weight_decay=oc["weight_decay"],
                                   freeze_step=extra.get("freeze_step", 100),
                                   max_coeff=oc["max_coeff"],
                                   min_coeff=oc["min_coeff"])
            return zero_one_adam(
                betas=oc["betas"], eps=oc["eps"],
                weight_decay=oc["weight_decay"],
                var_freeze_step=extra.get("var_freeze_step", 100000),
                var_update_scaler=extra.get("var_update_scaler", 16),
                adam_w_mode=oc["adam_w_mode"])
        if name == "adagrad":
            return adagrad(eps=oc["eps"], weight_decay=oc["weight_decay"])
        raise ValueError(f"Unknown optimizer {name!r} "
                         f"(supported: adam, adamw, lamb, adagrad, or pass an Optimizer)")

    def _configure_lr_scheduler(self, lr_scheduler):
        if lr_scheduler is not None:
            return lr_scheduler
        if self._config.scheduler_name:
            return get_lr_scheduler(self._config.scheduler_name,
                                    self._config.scheduler_params)
        return None

    def _configure_monitor(self):
        from ..monitor.monitor import MonitorMaster
        monitor = MonitorMaster(self._config.monitor_config)
        if self._config.monitor_config.enabled and not monitor.enabled \
                and dist.get_rank() == 0:
            log_dist("monitor enabled in config but no backend initialised "
                     "(see warnings above)", ranks=[0])
        return monitor

    def _configure_curriculum(self):
        """Legacy ``curriculum_learning`` block and the data-efficiency
        ``data_sampling.curriculum_learning`` block both produce one scheduler
        (reference ``engine.py`` curriculum_scheduler_legacy + data-efficiency wiring).
        The difficulty value is host state the data pipeline reads; ``train_batch``
        advances it each step."""
        cfg = None
        if self._config.curriculum_enabled_legacy:
            cfg = {k: v for k, v in self._config.curriculum_params_legacy.items()
                   if k != "enabled"}
        else:
            de = self._config.data_efficiency_config or {}
            cl = de.get("data_sampling", {}).get("curriculum_learning", {})
            if cl.get("enabled", False):
                cfg = {k: v for k, v in cl.items() if k != "enabled"}
        if cfg is None:
            return None
        from .data_pipeline.curriculum_scheduler import CurriculumScheduler
        return CurriculumScheduler(cfg)

    def get_data_difficulty(self) -> Optional[int]:
        """Current curriculum difficulty (None when curriculum is off)."""
        if self.curriculum_scheduler is None:
            return None
        return self.curriculum_scheduler.get_current_difficulty()

    def _configure_dataloader(self, training_data):
        if training_data is None:
            return None
        if hasattr(training_data, "__iter__") and not hasattr(training_data, "__getitem__"):
            return RepeatingLoader(training_data)
        local_batch = (self.train_micro_batch_size_per_gpu() *
                       max(1, self.mesh_spec.dp_world_size // dist.get_world_size()))
        return DeepSpeedDataLoader(
            training_data, batch_size=local_batch,
            num_replicas=dist.get_world_size(), rank=dist.get_rank(),
            collate_fn=self.collate_fn, drop_last=self._config.dataloader_drop_last)

    # ------------------------------------------------------------ state build
    def _build_state(self, scaler_state0: LossScaleState, seed: int):
        mesh = self.mesh_spec
        rng = jax.random.PRNGKey(seed)
        self._base_rng = rng

        if self.param_offload_enabled:
            self._build_param_offload_state(scaler_state0, rng)
            return

        abstract_params = jax.eval_shape(self.module.init_fn, rng)
        # compression scheduler (reference init_compression wiring in engine __init__)
        self._compression = None
        if self._config.compression_config:
            from ..compression.compress import init_compression
            sched = init_compression(abstract_params,
                                     {"compression_training":
                                      self._config.compression_config})
            if sched.active:
                self._compression = sched
        persist = self._config.zero_config.param_persistence_threshold
        self._param_spec_tree = param_specs(abstract_params, mesh, self.zero_stage,
                                            base_specs=self.module.param_specs,
                                            persistence_threshold=persist)
        self._param_shardings = to_shardings(self._param_spec_tree, mesh)
        # zero.Init equivalent: init jitted with sharded outputs — parameters are born
        # partitioned, never materialised replicated (partition_parameters.py:539).
        params = jax.jit(self.module.init_fn,
                         out_shardings=self._param_shardings)(rng)

        self._grad_spec_tree = grad_accum_specs(abstract_params, mesh, self.zero_stage,
                                                param_base_specs=self.module.param_specs)
        self._grad_shardings = to_shardings(self._grad_spec_tree, mesh)

        if self.offload_enabled:
            # Host tier owns fp32 masters + moments; HBM keeps only compute-dtype params.
            from .zero.offload import OffloadOptimizerTier
            oc = self._parse_optimizer_config()
            kind = "adagrad" if oc["name"] == "adagrad" else "adam"
            off_cfg = self._config.zero_config.offload_optimizer
            nvme_path = None
            if off_cfg.device == "nvme":
                if not off_cfg.nvme_path:
                    raise ValueError(
                        "offload_optimizer.device=nvme requires nvme_path")
                if kind != "adam":
                    raise ValueError("nvme offload supports adam/adamw only")
                nvme_path = off_cfg.nvme_path
            aio = self._config.aio_config
            self._offload_tier = OffloadOptimizerTier(
                params, self._param_shardings, self.compute_dtype, kind=kind,
                betas=oc["betas"], eps=oc["eps"], weight_decay=oc["weight_decay"],
                adam_w_mode=oc["adam_w_mode"], bias_correction=oc["bias_correction"],
                nvme_path=nvme_path,
                aio_config={"thread_count": aio.thread_count,
                            "block_size": aio.block_size,
                            "queue_depth": aio.queue_depth},
                grad_shardings=self._grad_shardings)
            del params
            params = self._offload_tier.initial_device_params()
            opt_state = ()
            self._opt_shardings = ()
        else:
            abstract_opt = jax.eval_shape(self.optimizer.init, abstract_params)
            self._opt_spec_tree = optimizer_state_specs(
                abstract_opt, mesh, self.zero_stage,
                abstract_params=abstract_params, param_spec_tree=self._param_spec_tree)
            self._opt_shardings = to_shardings(self._opt_spec_tree, mesh)
            opt_state = jax.jit(self.optimizer.init,
                                out_shardings=self._opt_shardings)(params)

        repl = mesh.replicated()
        self._scaler_shardings = jax.tree_util.tree_map(lambda _: repl, scaler_state0)
        self.state = TrainState(
            params=params,
            opt_state=opt_state,
            scaler=jax.device_put(scaler_state0, repl),
            global_step=jax.device_put(jnp.int32(0), repl),
            skipped_steps=jax.device_put(jnp.int32(0), repl),
        )
        self._state_shardings = TrainState(
            params=self._param_shardings,
            opt_state=self._opt_shardings,
            scaler=self._scaler_shardings,
            global_step=repl,
            skipped_steps=repl,
        )

    def _build_param_offload_state(self, scaler_state0: LossScaleState, rng):
        """ZeRO-3 param offload: no resident device state at all — the coordinator owns
        host masters, the optimizer, and the loss scaler. ``self.state`` is None in this
        mode; step/scale bookkeeping lives on host."""
        from .zero.param_offload import ParamOffloadCoordinator
        # compression scheduler from ABSTRACT params (no resident tree exists)
        self._compression = None
        if self._config.compression_config:
            from ..compression.compress import init_compression
            abstract_params = jax.eval_shape(self.module.init_fn, rng)
            sched = init_compression(abstract_params,
                                     {"compression_training":
                                      self._config.compression_config})
            if sched.active:
                self._compression = sched
        # QAT composes via the coordinator's push transform: every streamed key is
        # quantized on device right after its H2D push; grads w.r.t. the quantized
        # values update the fp32 masters (straight-through estimator — same
        # numerics as the resident engine's in-loss qat)
        qat_fn = None
        if self._compression is not None:
            comp = self._compression

            def qat_fn(key, tree, step):
                # per-key mini-tree {key: subtree} reproduces the full tree's leaf
                # paths, so the scheduler's path-matched plans apply identically
                return comp.qat({key: tree}, jnp.int32(step))[key]
        oc = self._parse_optimizer_config()
        kind = "adagrad" if oc["name"] == "adagrad" else "adam"
        op_cfg = self._config.zero_config.offload_param
        off_opt = self._config.zero_config.offload_optimizer
        nvme_path = None
        nvme_param_path = None
        # full ZeRO-Infinity: parameter masters (+ gradient accumulators) stream
        # from NVMe per model segment (reference partitioned_param_swapper.py:35);
        # implies the moment store on disk too
        if op_cfg.device == "nvme":
            if not op_cfg.nvme_path:
                raise ValueError("offload_param device=nvme requires nvme_path")
            if kind != "adam":
                raise ValueError("nvme offload supports adam/adamw only")
            nvme_param_path = op_cfg.nvme_path
        if off_opt is not None and off_opt.device == "nvme":
            if not off_opt.nvme_path:
                raise ValueError("offload_optimizer device=nvme requires nvme_path")
            if kind != "adam":
                raise ValueError("nvme offload supports adam/adamw only")
            nvme_path = off_opt.nvme_path
        aio = self._config.aio_config
        mesh = self.mesh_spec if self.mesh_spec.mesh.size > 1 else None
        self._param_offload = ParamOffloadCoordinator(
            self.module.segments, rng, self.compute_dtype, kind=kind,
            betas=oc["betas"], eps=oc["eps"], weight_decay=oc["weight_decay"],
            adam_w_mode=oc["adam_w_mode"], bias_correction=oc["bias_correction"],
            gradient_clipping=self._config.gradient_clipping or 0.0,
            fp16_enabled=self._config.fp16.enabled,
            loss_scaler=self.loss_scaler, scaler_state=scaler_state0,
            qat_fn=qat_fn,
            nvme_path=nvme_path, nvme_param_path=nvme_param_path,
            aio_config={"thread_count": aio.thread_count,
                        "block_size": aio.block_size,
                        "queue_depth": aio.queue_depth},
            mesh=mesh)
        self.state = None
        self._state_shardings = None

    # --------------------------------------------------------------- internals
    def _loss_and_scaled_grads(self, params, scale, batch, rng, step=None,
                               pld_theta=None):
        """value_and_grad in compute dtype against fp32 masters; loss scaled pre-diff.
        ``step`` (traced) gates the compression scheduler's QAT transforms;
        ``pld_theta`` (traced) reaches opt-in models (see ``_pld_in_loss``)."""

        def f(p):
            p = tree_cast(p, self.compute_dtype)
            if self._compression is not None and step is not None:
                p = self._compression.qat(p, step)
            kwargs = {}
            if self._pld_in_loss and pld_theta is not None:
                kwargs["pld_theta"] = pld_theta
            loss = self.module.loss_fn(p, batch, rng, **kwargs)
            if isinstance(loss, tuple):
                loss = loss[0]
            return loss * scale.astype(loss.dtype), loss

        (scaled, loss), grads = jax.value_and_grad(f, has_aux=True)(params)
        return loss, grads

    def _unscale_clip_and_check(self, state: TrainState, grads_acc, n_micro):
        """Shared device-side tail of both update paths: unscale by loss-scale × n_micro,
        prescale, global-norm overflow check, clip. Returns (grads, norm, overflow)."""
        scale = state.scaler.cur_scale
        grads = jax.tree_util.tree_map(
            lambda g: g / (scale * np.float32(n_micro)), grads_acc)
        if self._config.prescale_gradients:
            grads = jax.tree_util.tree_map(
                lambda g: g / np.float32(self._config.gradient_predivide_factor), grads)
        norm = global_norm(grads)
        if self._config.fp16.enabled:
            overflow = jnp.logical_not(jnp.isfinite(norm))
        else:
            overflow = jnp.array(False)
        clip = self._config.gradient_clipping
        if clip and clip > 0:
            safe_norm = jnp.where(jnp.isfinite(norm), norm, 1.0)
            grads = clip_by_global_norm(grads, clip, norm=safe_norm)
        return grads, norm, overflow

    def _apply_update(self, state: TrainState, grads_acc, lr, n_micro):
        """Unscale, clip, overflow-guard, optimizer update, scaler update."""
        scale = state.scaler.cur_scale
        grads, norm, overflow = self._unscale_clip_and_check(state, grads_acc, n_micro)
        new_params, new_opt = self.optimizer.update(grads, state.opt_state, state.params,
                                                    jnp.float32(lr))
        keep_old = lambda old, new: jnp.where(overflow, old, new)
        new_params = jax.tree_util.tree_map(keep_old, state.params, new_params)
        new_opt = jax.tree_util.tree_map(keep_old, state.opt_state, new_opt)
        new_scaler = self.loss_scaler.update(state.scaler, overflow)
        new_state = TrainState(
            params=new_params,
            opt_state=new_opt,
            scaler=new_scaler,
            global_step=state.global_step + 1,
            skipped_steps=state.skipped_steps + overflow.astype(jnp.int32),
        )
        metrics = {"grad_norm": norm, "overflow": overflow, "loss_scale": scale}
        return new_state, metrics

    def _finalize_grads_offload(self, state: TrainState, grads_acc, n_micro):
        """Offload-mode device-side tail: unscale, clip, overflow-check, scaler update.
        The optimizer update itself happens on host (see ``zero/offload.py``)."""
        scale = state.scaler.cur_scale
        grads, norm, overflow = self._unscale_clip_and_check(state, grads_acc, n_micro)
        new_scaler = self.loss_scaler.update(state.scaler, overflow)
        new_state = state._replace(
            scaler=new_scaler,
            global_step=state.global_step + 1,
            skipped_steps=state.skipped_steps + overflow.astype(jnp.int32))
        # D2H transfer dtype: bf16 halves the bytes and keeps fp32's exponent range, so
        # it is safe for unscaled grads; fp16's 5-bit exponent would flush exactly the
        # small-gradient range loss scaling exists to protect, so fp16 runs ship fp32.
        transfer_dtype = jnp.bfloat16 if self.compute_dtype == jnp.bfloat16 \
            else jnp.float32
        grads_out = tree_cast(grads, transfer_dtype)
        metrics = {"grad_norm": norm, "overflow": overflow, "loss_scale": scale}
        return new_state, grads_out, metrics

    def _build_train_step(self):
        """Fused whole-batch step: scan over gas microbatches, then update."""
        if self._quantized_dp:
            return self._build_train_step_quantized()
        gas = self.gradient_accumulation_steps()
        grad_shardings = self._grad_shardings

        def accumulate(state: TrainState, batch, pld_theta):
            step_rng = jax.random.fold_in(self._base_rng, state.global_step)

            def micro(acc, xs):
                mb, idx = xs
                rng = jax.random.fold_in(step_rng, idx)
                loss, grads = self._loss_and_scaled_grads(
                    state.params, state.scaler.cur_scale, mb, rng,
                    step=state.global_step, pld_theta=pld_theta)
                acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                acc = jax.lax.with_sharding_constraint(acc, grad_shardings)
                return acc, loss

            acc0 = jax.lax.with_sharding_constraint(
                tree_zeros_like(state.params, jnp.float32), grad_shardings)
            return jax.lax.scan(micro, acc0, (batch, jnp.arange(gas)))

        if self.offload_enabled:
            def train_step_offload(state: TrainState, batch, pld_theta):
                acc, losses = accumulate(state, batch, pld_theta)
                new_state, grads_out, metrics = self._finalize_grads_offload(
                    state, acc, gas)
                metrics["loss"] = jnp.mean(losses)
                return new_state, grads_out, metrics

            self._fns["train_step"] = jax.jit(
                train_step_offload, donate_argnums=(0,),
                out_shardings=(self._state_shardings, self._grad_shardings, None))
            return

        def train_step(state: TrainState, batch, lr, pld_theta):
            acc, losses = accumulate(state, batch, pld_theta)
            new_state, metrics = self._apply_update(state, acc, lr, gas)
            metrics["loss"] = jnp.mean(losses)
            return new_state, metrics

        jitted = jax.jit(train_step, donate_argnums=(0,),
                         out_shardings=(self._state_shardings, None))
        self._fns["train_step"] = jitted

    # --------------------------------------------- quantized DP gradient sync
    def _quantized_dp_regime(self) -> bool:
        """EQuARX-style int8 DP grad sync is wired for the plain-DP regime only
        (the same regime the reference's 1-bit optimizers target: replicated
        params, gradient allreduce over the data axis). Anything else keeps
        the full-precision XLA psum; a config that asks for more warns."""
        co = self.comm_overlap
        if not (co.enabled and co.quantized_allreduce):
            return False
        mesh = self.mesh_spec
        blockers = []
        if self.zero_stage != 0:
            blockers.append(f"zero_stage={self.zero_stage} (grads are sharded, "
                            "not replicated — XLA's reduce-scatter already "
                            "moves 1/W of the volume)")
        if self.offload_enabled or self.param_offload_enabled:
            blockers.append("offload tiers own the gradient pipeline")
        if mesh.size(AXIS_DATA) <= 1:
            blockers.append("no data axis > 1")
        others = [ax for ax in ("pipe", "fsdp", "expert", "seq", "tensor")
                  if mesh.size(ax) > 1]
        if others:
            blockers.append(f"non-DP mesh axes active: {others}")
        if blockers:
            logger.warning("comm_overlap.quantized_allreduce requested but "
                           "disabled: " + "; ".join(blockers))
            return False
        return True

    def _init_qar_residual(self):
        """Per-worker error-feedback residual: ``(W, *param.shape)`` fp32,
        sharded over the data axis (one fp32 copy per device). Optimizer-state
        adjacent but deliberately NOT in ``TrainState`` (and not checkpointed):
        restores reset it to zero, which costs one step of feedback — benign
        (documented in docs/PERF.md)."""
        mesh = self.mesh_spec
        W = mesh.size(AXIS_DATA)

        def shard_for(leaf):
            return mesh.sharding(P(AXIS_DATA, *([None] * leaf.ndim)))

        shardings = jax.tree_util.tree_map(shard_for, self.state.params)

        def zeros():
            return jax.tree_util.tree_map(
                lambda p: jnp.zeros((W,) + p.shape, jnp.float32),
                self.state.params)

        return jax.jit(zeros, out_shardings=shardings)(), shardings

    def _build_train_step_quantized(self):
        """Fused step with int8 blockwise-scaled DP gradient sync.

        The microbatch scan + grad computation runs INSIDE a ``shard_map``
        manual over the data axis, so gradients stay LOCAL (per-shard batch
        mean) instead of being full-precision-psummed by GSPMD; the exchange
        is ``comm.compressed.quantized_allreduce`` — int8 payload + per-block
        scales + error feedback, ~3.9x less wire volume. Semantics: the synced
        gradient is the mean of shard means (exactly torch-DDP/reference DP
        averaging; equal to the global mean when shards hold equal valid-token
        counts).
        """
        from ..comm.compressed import quantized_allreduce
        from ..utils.jax_compat import shard_map
        gas = self.gradient_accumulation_steps()
        mesh = self.mesh_spec
        W = mesh.size(AXIS_DATA)
        block = self.comm_overlap.quant_block
        self._qar_residual, self._qar_shardings = self._init_qar_residual()
        n_elems = sum(int(np.prod(l.shape))
                      for l in jax.tree_util.tree_leaves(self.state.params))
        # per-worker on-wire: two 8-bit phases (a2a reduce-scatter + requantized
        # gather), each (W-1)/W of payload + block scales
        record_collective(
            "dp.grad_sync", "quantized_allreduce",
            2 * (W - 1) * (n_elems + 4 * ((n_elems + block - 1) // block)) // W,
            W, overlapped=False)

        def local_sync(params, scale, batch, step_key, step, theta, residual):
            # trace-time: hide the global mesh so model internals take their
            # local (non-GSPMD, non-shard_map) paths inside this manual region
            prev = get_global_mesh()
            set_global_mesh(None)
            try:
                # dropout/gating noise must stay i.i.d. across the batch: the
                # baseline path draws one mask over the GLOBAL batch, so the
                # local draw here must be per-shard-keyed or every DP shard
                # repeats the same mask at local-batch shape
                shard_key = jax.random.fold_in(
                    step_key, jax.lax.axis_index(AXIS_DATA))

                def micro(acc, xs):
                    mb, idx = xs
                    rng = jax.random.fold_in(shard_key, idx)
                    loss, grads = self._loss_and_scaled_grads(
                        params, scale, mb, rng, step=step, pld_theta=theta)
                    return jax.tree_util.tree_map(jnp.add, acc, grads), loss

                acc0 = tree_zeros_like(params, jnp.float32)
                acc, losses = jax.lax.scan(micro, acc0, (batch, jnp.arange(gas)))
            finally:
                set_global_mesh(prev)
            denom = scale * np.float32(gas)
            if self._config.prescale_gradients:
                denom = denom * np.float32(self._config.gradient_predivide_factor)
            g = jax.tree_util.tree_map(lambda v: v / denom, acc)
            flat_g, treedef = jax.tree_util.tree_flatten(g)
            flat_r = jax.tree_util.tree_leaves(residual)
            finite = jnp.array(True)
            for leaf in flat_g:
                finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(leaf)))
            # One fused collective over the concatenated gradient: per-leaf
            # dispatch would pad every bias/LN leaf up to block*W and issue
            # hundreds of tiny sequential collectives — more wire than the
            # fp32 ring it replaces. Concatenating amortizes the pad to a
            # single <= block*W tail and keeps the 3.9x volume win.
            sizes = [int(np.prod(l.shape)) for l in flat_g]
            bounds = np.cumsum([0] + sizes)
            g_cat = jnp.concatenate([l.reshape(-1) for l in flat_g])
            r_cat = jnp.concatenate([rl[0].reshape(-1) for rl in flat_r])
            s_cat, res_cat = quantized_allreduce(
                g_cat, r_cat, AXIS_DATA, block=block)
            synced = [s_cat[bounds[i]:bounds[i + 1]].reshape(l.shape)
                      for i, l in enumerate(flat_g)]
            new_res = [res_cat[bounds[i]:bounds[i + 1]].reshape(
                           (1,) + l.shape)
                       for i, l in enumerate(flat_g)]
            g_sync = jax.tree_util.tree_unflatten(treedef, synced)
            residual_out = jax.tree_util.tree_unflatten(treedef, new_res)
            loss_mean = jax.lax.psum(jnp.mean(losses), AXIS_DATA) / np.float32(W)
            overflow = jax.lax.pmax(
                jnp.logical_not(finite).astype(jnp.int32), AXIS_DATA)
            return g_sync, residual_out, loss_mean, overflow

        repl = P()

        def train_step(state: TrainState, batch, lr, theta, residual):
            params_spec = jax.tree_util.tree_map(lambda _: repl, state.params)
            batch_spec = jax.tree_util.tree_map(
                lambda leaf: P(None, AXIS_DATA, *([None] * (leaf.ndim - 2))),
                batch)
            res_spec = jax.tree_util.tree_map(
                lambda leaf: P(AXIS_DATA, *([None] * (leaf.ndim - 1))), residual)
            step_key = jax.random.fold_in(self._base_rng, state.global_step)
            mapped = shard_map(
                local_sync, mesh=mesh.mesh, axis_names={AXIS_DATA},
                in_specs=(params_spec, repl, batch_spec, repl, repl, repl,
                          res_spec),
                out_specs=(params_spec, res_spec, repl, repl),
                check_vma=False)
            g_sync, new_residual, loss_mean, overflow_q = mapped(
                state.params, state.scaler.cur_scale, batch, step_key,
                state.global_step, theta, residual)
            # tail matches _apply_update, with grads already unscaled/averaged.
            # Unlike the full-precision path (where a NaN grad propagates into
            # params and is VISIBLE), quantized_allreduce zeroes non-finite
            # values before the int8 cast — so the overflow flag must gate the
            # update at every precision, not just under fp16 loss scaling, or
            # a bf16/fp32 overflow step would be silently applied as zeros.
            norm = global_norm(g_sync)
            overflow = jnp.logical_or(overflow_q > 0,
                                      jnp.logical_not(jnp.isfinite(norm)))
            clip = self._config.gradient_clipping
            if clip and clip > 0:
                safe_norm = jnp.where(jnp.isfinite(norm), norm, 1.0)
                g_sync = clip_by_global_norm(g_sync, clip, norm=safe_norm)
            new_params, new_opt = self.optimizer.update(
                g_sync, state.opt_state, state.params, jnp.float32(lr))
            keep_old = lambda old, new: jnp.where(overflow, old, new)
            new_params = jax.tree_util.tree_map(keep_old, state.params, new_params)
            new_opt = jax.tree_util.tree_map(keep_old, state.opt_state, new_opt)
            # EF contract assumes the transmitted grad was CONSUMED; a skipped
            # step discards it, so committing the new residual would inject a
            # phantom correction into step k+1 — keep the pre-step residual
            new_residual = jax.tree_util.tree_map(keep_old, residual, new_residual)
            new_state = TrainState(
                params=new_params, opt_state=new_opt,
                scaler=self.loss_scaler.update(state.scaler, overflow),
                global_step=state.global_step + 1,
                skipped_steps=state.skipped_steps + overflow.astype(jnp.int32))
            metrics = {"loss": loss_mean, "grad_norm": norm,
                       "overflow": overflow,
                       "loss_scale": state.scaler.cur_scale}
            return new_state, metrics, new_residual

        self._fns["train_step"] = jax.jit(
            train_step, donate_argnums=(0, 4),
            out_shardings=(self._state_shardings, None, self._qar_shardings))

    def _build_micro_fns(self):
        """Eager-compatible forward/backward/step path (reference API)."""
        grad_shardings = self._grad_shardings

        def fwd_bwd(params, scale, batch, rng, step, pld_theta):
            loss, grads = self._loss_and_scaled_grads(params, scale, batch, rng,
                                                      step=step,
                                                      pld_theta=pld_theta)
            # fp32 accumulation regardless of param dtype (the fused path's acc0 is fp32;
            # bf16/fp16 accumulation across microbatches would drop small contributions)
            grads = tree_cast(grads, jnp.float32)
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            return loss, grads

        self._fns["fwd_bwd"] = jax.jit(fwd_bwd, out_shardings=(None, grad_shardings))
        self._fns["acc_add"] = jax.jit(
            lambda acc, g: jax.tree_util.tree_map(jnp.add, acc, g),
            donate_argnums=(0,), out_shardings=grad_shardings)

        if self.offload_enabled:
            self._fns["finalize_offload"] = jax.jit(
                self._finalize_grads_offload, static_argnums=(2,), donate_argnums=(0,),
                out_shardings=(self._state_shardings, self._grad_shardings, None))
        else:
            def apply_step(state, acc, lr, n_micro):
                return self._apply_update(state, acc, lr, n_micro)

            self._fns["apply_step"] = jax.jit(
                apply_step, static_argnums=(3,), donate_argnums=(0,),
                out_shardings=(self._state_shardings, None))

        def eval_step(params, batch, rng):
            loss = self.module.loss_fn(tree_cast(params, self.compute_dtype), batch, rng)
            return loss[0] if isinstance(loss, tuple) else loss

        self._fns["eval_step"] = jax.jit(eval_step)

    # ------------------------------------------------------------- data plumbing
    def _globalize(self, local_batch, leading_gas: bool = False):
        """Assemble process-local numpy batch into globally-sharded jax.Arrays."""
        mesh = self.mesh_spec

        def one(leaf):
            leaf = np.asarray(leaf)
            batch_axes = tuple(ax for ax in ("data", "fsdp", "expert")
                               if mesh.size(ax) > 1) or None
            if leading_gas:
                spec = [None, batch_axes] + [None] * (leaf.ndim - 2)
            else:
                spec = [batch_axes] + [None] * (leaf.ndim - 1)
            sharding = NamedSharding(mesh.mesh, P(*spec))
            if dist.get_world_size() == 1:
                return jax.device_put(leaf, sharding)
            return jax.make_array_from_process_local_data(sharding, leaf)

        return jax.tree_util.tree_map(one, local_batch)

    def _reshape_for_gas(self, batch):
        gas = self.gradient_accumulation_steps()

        def one(leaf):
            leaf = np.asarray(leaf)
            if not (leaf.shape[0] % gas == 0):
                raise AssertionError(f"train_batch leading dim {leaf.shape[0]} not divisible by "
                 f"gradient_accumulation_steps {gas}")
            return leaf.reshape(gas, leaf.shape[0] // gas, *leaf.shape[1:])

        return jax.tree_util.tree_map(one, batch)

    # ------------------------------------------------------------------- API
    def train_batch(self, batch=None, data_iter=None):
        """Process one full global batch (gas microbatches) and take an optimizer step.

        Mirrors ``PipelineEngine.train_batch`` (reference ``pipe/engine.py:295``) as the fused
        path for the base engine.
        """
        if batch is None:
            if data_iter is not None:
                batch = next(data_iter)
            elif self.training_dataloader is not None:
                batch = self._next_train_batch()
            else:
                raise ValueError("train_batch needs batch=, data_iter=, or training_data")
        # jitted steps trace LAZILY (at first call, not at jit()): another
        # engine constructed since __init__ may have swapped the global mesh /
        # overlap config, so re-assert ours before anything can trace — same
        # defense InferenceEngine applies in its compiled-fn dispatch
        set_global_mesh(self.mesh_spec)
        set_overlap_config(self.comm_overlap)
        if self.param_offload_enabled:
            return self._train_batch_param_offload(batch)
        first_trace = "train_step" not in self._fns
        if first_trace:
            # isolate this engine's span capture: build-time records
            # (dp.grad_sync) land during _build_train_step, trace-time records
            # (RowParallelDense / MoE exchange) during the first jitted call
            collective_spans.reset()
            self._build_train_step()
        jitted = self._fns["train_step"]
        local = self._reshape_for_gas(batch)
        gbatch = self._globalize(local, leading_gas=True)

        fp_cfg = self._config.flops_profiler
        if fp_cfg.enabled and self._host_steps + 1 == fp_cfg.profile_step:
            self._run_flops_profiler(gbatch)

        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        lr = np.float32(self.get_lr_value())
        theta = np.float32(self.progressive_layer_drop.get_theta()
                           if self.progressive_layer_drop is not None else 1.0)
        tracer = get_tracer()
        self._step_t0 = time.perf_counter()
        self._last_step_tokens = _batch_tokens(batch)
        step_span = tracer.begin("train_step", cat=CAT_TRAIN, tid="train",
                                 attrs={"step": self._host_steps + 1})
        with annotate("train_step"):
            if self.offload_enabled:
                self.state, grads, metrics = jitted(self.state, gbatch, theta)
                self._host_optimizer_step(grads, lr, metrics)
            elif self._quantized_dp:
                self.state, metrics, self._qar_residual = jitted(
                    self.state, gbatch, lr, theta, self._qar_residual)
            else:
                self.state, metrics = jitted(self.state, gbatch, lr, theta)
        if first_trace:
            self._comm_spans = collective_spans.summary()
        if step_span is not None:
            # tracing-enabled mode pays one sync so the span covers the device
            # work, not just the async dispatch (disabled mode never syncs)
            jax.block_until_ready(metrics["loss"])  # lint: host-sync-ok (tracer-gated)
            # grad sync is XLA-scheduled inside the step: host wall-time can't
            # split it out, but the trace-time byte accounting can ride the
            # step's trace as a MODELED child span
            if spans_total_bytes(self._comm_spans):
                tracer.instant(
                    "grad_sync", step_span, cat=CAT_TRAIN,
                    attrs={"modeled": True,
                           "bytes_on_wire": spans_total_bytes(self._comm_spans),
                           "overlap_ratio":
                               spans_overlap_ratio(self._comm_spans)})
            tracer.end_span(step_span)
        obs_profiler.tick("train_step")
        self.timers(TRAIN_BATCH_TIMER).stop(sync=False)
        self.tput_timer.stop(global_step=True)

        # Host-side step mirror: the device counter (state.global_step) is exact but reading
        # it forces a device sync per step; cadence decisions (print/monitor) use this mirror
        # so the hot path never stalls the async dispatch queue. (Under fp16 overflow-skip the
        # two can drift by the number of skipped steps; exact value remains at .global_steps.)
        self._host_steps += 1
        self.micro_steps += self.gradient_accumulation_steps()
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        if self.curriculum_scheduler is not None:
            self.curriculum_scheduler.update_difficulty(self._host_steps)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self._host_steps)
        self._last_metrics = metrics
        self._write_monitor_events(metrics)
        if self._host_steps % self._config.steps_per_print == 0:
            # lint: host-sync-ok (steps_per_print-gated: syncs only on print steps)
            log_dist(f"step={self._host_steps} loss={float(metrics['loss']):.4f} "
                     f"lr={float(lr):.3e} loss_scale={float(metrics['loss_scale']):.0f}",
                     ranks=[0])
            if self._config.wall_clock_breakdown:
                # reference engine.py wall_clock_breakdown: per-phase timer means each
                # print interval (the fused path has one phase; the eager path adds
                # fwd/bwd/step)
                names = [n for n in (TRAIN_BATCH_TIMER, FORWARD_GLOBAL_TIMER,
                                     BACKWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER)
                         if self.timers.has_timer(n)]
                self.timers.log(names)
        return metrics["loss"]

    def _train_batch_param_offload(self, batch):
        """Streamed whole-batch step (ZeRO-3 param offload): the coordinator runs the
        per-segment fwd/bwd stream and the host optimizer; no fused jitted step exists
        because the full parameter tree is never device-resident."""
        gas = self.gradient_accumulation_steps()
        local = self._reshape_for_gas(batch)
        micros = [self._globalize(jax.tree_util.tree_map(lambda l: l[i], local))
                  for i in range(gas)]
        fp_cfg = self._config.flops_profiler
        if fp_cfg.enabled and self._host_steps + 1 == fp_cfg.profile_step:
            self._run_flops_profiler_offload(micros[0])
        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        self._step_t0 = time.perf_counter()
        self._last_step_tokens = _batch_tokens(batch)
        lr = np.float32(self.get_lr_value())
        rng = jax.random.fold_in(self._base_rng, self._host_steps)
        tracer = get_tracer()
        step_span = tracer.begin("train_step", cat=CAT_TRAIN, tid="train",
                                 attrs={"step": self._host_steps + 1,
                                        "offload": True})
        with annotate("train_step"):
            metrics = self._param_offload.train_step(micros, lr=float(lr),
                                                     rng=rng)
        tracer.end_span(step_span)       # streamed step is host-synchronous
        obs_profiler.tick("train_step")
        self.timers(TRAIN_BATCH_TIMER).stop(sync=False)
        self.tput_timer.stop(global_step=True)
        self._host_steps += 1
        self.micro_steps += gas
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        if self.curriculum_scheduler is not None:
            self.curriculum_scheduler.update_difficulty(self._host_steps)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self._host_steps)
        self._last_metrics = metrics
        self._write_monitor_events(metrics)
        if self._host_steps % self._config.steps_per_print == 0:
            log_dist(f"step={self._host_steps} loss={metrics['loss']:.4f} "
                     f"lr={float(lr):.3e} "
                     f"loss_scale={metrics['loss_scale']:.0f}", ranks=[0])
        return metrics["loss"]

    def _host_optimizer_step(self, grads, lr, metrics):
        """Offload mode: host Adam on fp32 masters, push compute-dtype params H2D.
        The overflow read only syncs under fp16 (the offload path is host-synchronous at
        the grad fetch anyway)."""
        skip = bool(metrics["overflow"]) if self._config.fp16.enabled else False
        new_params = self._offload_tier.step(grads, lr=float(lr), skip=skip)
        if new_params is not None:
            self.state = self.state._replace(params=new_params)

    def _run_flops_profiler_offload(self, micro):
        """Flops profile of the STREAMED step (offload_param): trace the composed
        per-segment fwd+bwd of one microbatch over ABSTRACT parameters — no
        full-model device materialisation, same jaxpr/XLA accounting as the fused
        path's profile."""
        from ..profiling.flops_profiler import FlopsProfiler
        co = self._param_offload
        profiler = FlopsProfiler(self._config.flops_profiler)

        def abs_key(key):
            leaves = [jax.ShapeDtypeStruct(s, self.compute_dtype)
                      for s in co.key_shapes[key]]
            return jax.tree_util.tree_unflatten(co.key_treedef[key], leaves)

        params_t = tuple(tuple(abs_key(k) for k in seg.param_keys)
                         for seg in co.segments)
        G = len(co.segments)

        def step_fn(seg_params, batch, rng):
            xs = [None] * G
            x = None
            for g in range(G - 1):
                srng = jax.random.fold_in(rng, g)
                if co.segments[g].kind == "first":
                    x = co._fwd(g)(seg_params[g], batch, srng)
                else:
                    xs[g] = x
                    x = co._fwd(g)(seg_params[g], x, batch, srng)
            xs[G - 1] = x
            gout, loss = None, None
            grads = []
            for g in range(G - 1, -1, -1):
                srng = jax.random.fold_in(rng, g)
                seg = co.segments[g]
                if seg.kind == "last":
                    loss, gp, gout = co._bwd(g)(seg_params[g], xs[g], batch,
                                                srng, jnp.float32(1.0))
                elif seg.kind == "mid":
                    gp, gout = co._bwd(g)(seg_params[g], xs[g], batch, srng,
                                          gout)
                else:
                    gp = co._bwd(g)(seg_params[g], batch, srng, gout)
                grads.append(gp)
            return loss, grads

        try:
            profiler.profile_step(step_fn, params_t, micro,
                                  jax.random.PRNGKey(0),
                                  depth=self._config.flops_profiler.module_depth
                                  if self._config.flops_profiler.module_depth >= 0
                                  else 2)
            sps = self.tput_timer.avg_samples_per_sec() or None
            tput = (sps / self.train_batch_size()) if sps else None
            profiler.print_model_profile(throughput_per_sec=tput)
            self.flops_profiler = profiler
        except Exception as e:
            log_dist(f"flops profiler failed: {e}", ranks=[0])

    def _run_flops_profiler(self, gbatch):
        """One-shot train-step profile at ``flops_profiler.profile_step``
        (reference ``engine.py:1791-1800`` wiring)."""
        from ..profiling.flops_profiler import FlopsProfiler
        profiler = FlopsProfiler(self._config.flops_profiler)
        lr = np.float32(self.get_lr_value())

        def step_fn(state, batch):
            jitted = self._fns["train_step"]
            theta = np.float32(1.0)
            if self.offload_enabled:
                return jitted(state, batch, theta)
            if self._quantized_dp:
                return jitted(state, batch, lr, theta, self._qar_residual)
            return jitted(state, batch, lr, theta)

        try:
            profiler.profile_step(lambda s, b: step_fn(s, b), self.state, gbatch,
                                  depth=self._config.flops_profiler.module_depth
                                  if self._config.flops_profiler.module_depth >= 0 else 2)
            sps = self.tput_timer.avg_samples_per_sec() or None
            tput = (sps / self.train_batch_size()) if sps else None
            profiler.print_model_profile(throughput_per_sec=tput)
            self.flops_profiler = profiler
        except Exception as e:
            log_dist(f"flops profiler failed: {e}", ranks=[0])

    def _next_train_batch(self):
        if not hasattr(self, "_train_iter") or self._train_iter is None:
            loader = self.training_dataloader
            self._train_iter = loader if hasattr(loader, "__next__") \
                else iter(RepeatingLoader(loader))
        gas = self.gradient_accumulation_steps()
        micros = [next(self._train_iter) for _ in range(gas)]
        return jax.tree_util.tree_map(lambda *xs: np.concatenate(xs, axis=0), *micros)

    def forward(self, batch):
        """Compute loss for one microbatch; gradients are computed alongside and cached
        (JAX cannot split forward from backward), to be consumed by ``backward()``."""
        if self.param_offload_enabled:
            raise NotImplementedError(
                "the eager forward()/backward()/step() triple is unavailable under "
                "offload_param (no resident parameter tree) — use train_batch()")
        # re-assert trace environment (see train_batch): fwd_bwd traces on
        # first call and must see THIS engine's mesh + overlap setting
        set_global_mesh(self.mesh_spec)
        set_overlap_config(self.comm_overlap)
        first_trace = "fwd_bwd" not in self._fns
        if first_trace:
            collective_spans.reset()
            self._build_micro_fns()
        self.timers(FORWARD_GLOBAL_TIMER).start()
        gb = self._globalize(batch)
        rng = jax.random.fold_in(
            jax.random.fold_in(self._base_rng, self.state.global_step), self.micro_steps)
        theta = np.float32(self.progressive_layer_drop.get_theta()
                           if self.progressive_layer_drop is not None else 1.0)
        loss, grads = self._fns["fwd_bwd"](self.state.params,
                                           self.state.scaler.cur_scale,
                                           gb, rng, self.state.global_step, theta)
        if first_trace:
            self._comm_spans = collective_spans.summary()
        self._cached_grads = grads
        self._cached_loss = loss
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        return loss

    __call__ = forward

    def backward(self, loss=None, allreduce_gradients: bool = True):
        """Fold the cached microbatch gradients into the accumulator.

        Reference semantics: ``engine.backward(loss)`` (``engine.py:1932``). The reduction
        across data-parallel devices happens inside XLA when the accumulator's sharded spec
        forces it (stage >= 2) or at update time (psum via replicated spec).
        """
        if not (self._cached_grads is not None):
            raise AssertionError("backward() called before forward()")
        if loss is not None and loss is not self._cached_loss \
                and not getattr(self, "_loss_mismatch_warned", False):
            # the cached grads differentiate the loss forward() computed — a
            # transformed/recomputed loss here would be silently ignored (JAX
            # cannot re-run autograd from a detached scalar, unlike torch)
            logger.warning(
                "backward(loss) received a different object than forward() "
                "returned; gradients correspond to forward()'s loss — any "
                "transformation applied in between does NOT reach the "
                "gradients. Fold scaling/additions into the model's loss_fn.")
            self._loss_mismatch_warned = True
        if self._grad_acc is None:
            self._grad_acc = self._cached_grads
        else:
            self._grad_acc = self._fns["acc_add"](self._grad_acc, self._cached_grads)
        self._cached_grads = None
        self._cached_loss = None
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        """Reference ``engine.py:is_gradient_accumulation_boundary``."""
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def step(self):
        """Optimizer step at gradient-accumulation boundaries (no-op otherwise).

        Reference ``engine.py:2143 step`` / ``_take_model_step:2075``.
        """
        if "fwd_bwd" not in self._fns:
            self._build_micro_fns()
        take_step = self.is_gradient_accumulation_boundary()
        self.micro_steps += 1
        if not take_step:
            return
        if not (self._grad_acc is not None):
            raise AssertionError("step() called with no accumulated gradients")
        self.timers(STEP_GLOBAL_TIMER).start()
        lr = np.float32(self.get_lr_value())
        if self.offload_enabled:
            self.state, grads, metrics = self._fns["finalize_offload"](
                self.state, self._grad_acc, self.gradient_accumulation_steps())
            self._host_optimizer_step(grads, lr, metrics)
        else:
            self.state, metrics = self._fns["apply_step"](
                self.state, self._grad_acc, lr, self.gradient_accumulation_steps())
        self._grad_acc = None
        self._host_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        if self.curriculum_scheduler is not None:
            self.curriculum_scheduler.update_difficulty(self._host_steps)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self._host_steps)
        self._last_metrics = metrics
        self.timers(STEP_GLOBAL_TIMER).stop(sync=False)
        self._write_monitor_events(metrics)

    def eval_batch(self, batch):
        gb = self._globalize(batch)
        # dedicated eval rng stream, disjoint from the train stream by construction: train
        # keys derive from fold_in(_base_rng, global_step) with global_step a non-negative
        # int32, so folding -1 (0xFFFFFFFF as uint32, outside that range) roots a branch no
        # train step can reach
        self._eval_calls = getattr(self, "_eval_calls", 0) + 1
        rng = jax.random.fold_in(jax.random.fold_in(self._base_rng, 0xFFFFFFFF), self._eval_calls)
        if self.param_offload_enabled:
            return self._param_offload.eval_loss(gb, rng)
        if "eval_step" not in self._fns:
            self._build_micro_fns()
        return self._fns["eval_step"](self.state.params, gb, rng)

    def set_monitor(self, monitor):
        """Attach/replace the MonitorMaster at runtime (mirrors
        ``InferenceEngine.set_monitor``); per-step ``Train/*`` events — loss,
        lr, step time, tokens/sec, and (when the flops profiler has run)
        modeled MFU — flow to it and to the observability registry."""
        self.monitor = monitor
        return self

    def _modeled_mfu(self, step_time_s: float) -> Optional[float]:
        """Modeled model-flops utilization: profiled step flops / step wall
        time / aggregate peak. Needs both a flops-profiler result (run the
        profiler via ``flops_profiler.profile_step``) and a per-chip peak —
        ``flops_profiler.peak_tflops`` in config, or the device-kind table
        for known TPUs. The profiled flops cover the whole GLOBAL-batch step,
        so the peak is per-chip × device count."""
        prof = getattr(self, "flops_profiler", None)
        if prof is None or prof.result is None or step_time_s <= 0:
            return None
        peak_tflops = self._config.flops_profiler.peak_tflops
        if peak_tflops is None:
            peak_tflops = _PEAK_TFLOPS_BY_KIND.get(
                jax.devices()[0].device_kind.lower())
        if not peak_tflops:
            return None
        achieved = prof.result.total_flops / step_time_s / 1e12
        return achieved / (float(peak_tflops) * jax.device_count())

    def _write_monitor_events(self, metrics):
        # Train/* export (monitor AND registry) is gated on an enabled monitor
        # ON PURPOSE, unlike the inference engine's unconditional registry
        # records: building these events calls float(loss) — a per-step device
        # sync that stalls the async dispatch queue. generate() already syncs
        # for TTFT so its records are free; a monitor-less training loop must
        # stay fully pipelined. To export Train/* to the registry alone,
        # attach any cheap backend (jsonl) or engine.set_monitor(...).
        if self.monitor is None or not getattr(self.monitor, "enabled", False):
            return
        step = self._host_steps
        # lint: host-sync-ok (the documented Train/* monitor-gated sync: the
        # guard above returns unless a monitor is attached)
        events = [("Train/Samples/train_loss", float(metrics.get("loss", 0.0)), step),
                  ("Train/Samples/lr", self.get_lr_value(), step)]
        if self._config.fp16.enabled:
            # lint: host-sync-ok (monitor-gated, same guard)
            events.append(("Train/Samples/loss_scale",
                           float(metrics["loss_scale"]), step))
        if spans_total_bytes(self._comm_spans):
            # per-trace bytes-on-wire estimates from the decomposed-collective
            # call sites, snapshotted at THIS engine's first trace (the global
            # accumulator blends every engine's traces in the process)
            # lint: host-sync-ok (host-side span math, no device value)
            events.append(("Train/Comm/bytes_on_wire",
                           float(spans_total_bytes(self._comm_spans)), step))
            events.append(("Train/Comm/overlap_ratio",
                           spans_overlap_ratio(self._comm_spans), step))
        # step wall time, honest: the float(loss) above already forced the
        # device sync, so the clock covers the whole step, not the dispatch
        t0 = getattr(self, "_step_t0", None)
        if t0 is not None:
            step_time = time.perf_counter() - t0
            self._step_t0 = None
            events.append(("Train/step_time_ms", step_time * 1e3, step))
            tokens = getattr(self, "_last_step_tokens", 0)
            if tokens and step_time > 0:
                events.append(("Train/tokens_per_sec", tokens / step_time,
                               step))
            mfu = self._modeled_mfu(step_time)
            if mfu is not None:
                events.append(("Train/mfu", mfu, step))
        obs_record_events(events)        # process registry (exposition)
        self.monitor.write_events(events)

    # ------------------------------------------------------------- properties
    @property
    def global_steps(self) -> int:
        if self.state is None:
            return self._host_steps
        return int(self.state.global_step)

    @property
    def skipped_steps(self) -> int:
        if self.state is None:
            return self._param_offload.skipped_steps
        return int(self.state.skipped_steps)

    def get_global_grad_norm(self) -> float:
        return float(self._last_metrics.get("grad_norm", 0.0))

    def loss_scale(self) -> float:
        if self.state is None:
            return self._param_offload._cur_scale()
        return float(self.state.scaler.cur_scale)

    def get_lr_value(self) -> float:
        if self.lr_scheduler is not None:
            lrs = self.lr_scheduler.get_last_lr()
            if self.lr_scheduler.last_batch_iteration < 0:
                self.lr_scheduler.step(0)
                lrs = self.lr_scheduler.get_last_lr()
            return float(lrs[0])
        return float(getattr(self, "_base_lr", 1e-3))

    def get_lr(self):
        return [self.get_lr_value()]

    def train_batch_size(self) -> int:
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self) -> int:
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self._config.gradient_accumulation_steps

    def zero_optimization_stage(self) -> int:
        return self.zero_stage

    def get_batch_info(self):
        return (self.train_batch_size(), self.train_micro_batch_size_per_gpu(),
                self.gradient_accumulation_steps())

    # ------------------------------------------------------------ checkpointing
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[dict] = None, save_latest: bool = True):
        """Reference ``engine.py:3085``. Orbax writes sharded arrays once across hosts; the
        result is re-shardable to any topology (universal checkpoint by construction).

        Crash-consistent: all data is staged into ``<save_dir>/<tag>.tmp`` and
        published by ``commit_tag`` (manifest + fsync + one atomic rename); the
        ``latest`` pointer advances only after the rename lands, so a kill at any
        point leaves the previous committed tag loadable (see
        ``docs/FAULT_TOLERANCE.md``)."""
        tag = tag or f"global_step{self.global_steps}"
        # rank 0 alone reclaims stale staging (a racing reclaim would rmtree
        # peers' in-flight writes on a shared filesystem); peers join the
        # staging dir only after the barrier
        if dist.get_rank() == 0:
            path = self.checkpoint_engine.begin_tag(save_dir, tag)
        else:
            path = self.checkpoint_engine.staging_path(save_dir, tag)
        dist.barrier("ckpt_begin")
        if dist.get_rank() != 0:
            os.makedirs(path, exist_ok=True)
        fault_point("ckpt.save.begin")
        if self.param_offload_enabled:
            # the full model exists only as host fp32 masters — serialize those (plus
            # moments/scaler) as the checkpoint; there is no device state to save
            self._param_offload.save_to(self.checkpoint_engine,
                                        os.path.join(path, "offload_state"))
        else:
            self.checkpoint_engine.save(self.state._asdict(),
                                        os.path.join(path, "state"))
        if self.offload_enabled:
            # host-resident fp32 masters + moments (reference: offloaded optimizer
            # partitions serialize through the same checkpoint, stage_1_and_2.py:2235);
            # the NVMe tier streams its moment files by copy, never through RAM
            self._offload_tier.save_to(self.checkpoint_engine,
                                       os.path.join(path, "offload_state"))
        side = {
            "global_step": self.global_steps,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "mesh_axis_sizes": self.mesh_spec.axis_sizes,
            "lr_scheduler": (self.lr_scheduler.state_dict()
                             if self.lr_scheduler is not None else None),
            "client_state": client_state or {},
        }
        self.checkpoint_engine.save(side, os.path.join(path, "client_state.pkl"))
        dist.barrier("ckpt_save")
        # non-zero ranks drain their async writes, then a barrier proves every
        # peer's shards are durable BEFORE rank 0 hashes the manifest and
        # renames (commit_tag drains rank 0's own writer internally) — a crash
        # anywhere before the rename leaves 'latest' at the previous durable tag
        if dist.get_rank() != 0:
            self.checkpoint_engine.commit(tag)
        dist.barrier("ckpt_drain")
        tracer = get_tracer()
        commit_span = tracer.begin("checkpoint_commit", cat=CAT_TRAIN,
                                   tid="train",
                                   attrs={"tag": str(tag),
                                          "step": self._host_steps})
        if dist.get_rank() == 0:
            final = self.checkpoint_engine.commit_tag(save_dir, tag)
        else:
            final = os.path.join(save_dir, str(tag))
        dist.barrier("ckpt_commit")
        if save_latest and dist.get_rank() == 0:
            write_latest_pointer(save_dir, tag)
        tracer.end_span(commit_span)
        return final

    def _resolve_load_tag(self, load_dir: str, tag: Optional[str]):
        """Tag resolution with torn-checkpoint fallback: an explicit ``tag`` is
        trusted (validation still runs at load); otherwise follow ``latest``,
        and when it names a missing/uncommitted tag, fall back to the newest
        committed tag on disk."""
        if tag is not None:
            return str(tag)
        latest_path = os.path.join(load_dir, LATEST_FILE)
        pointed = None
        if os.path.isfile(latest_path):
            with open(latest_path) as f:
                pointed = f.read().strip()
        if pointed and is_committed_tag(load_dir, pointed):
            return pointed
        fallback = find_latest_committed_tag(load_dir, exclude=pointed)
        if fallback is not None:
            if pointed:
                logger.error(
                    f"[ckpt] '{LATEST_FILE}' points at {pointed!r} which is "
                    f"missing or uncommitted — falling back to newest committed "
                    f"tag {fallback!r}")
            return fallback
        if pointed:
            # nothing committed to fall back to: surface the torn tag loudly
            return pointed
        return None

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True,
                        load_module_only: bool = False,
                        validate: bool = True):
        """Reference ``engine.py:2725``. Restores into the CURRENT mesh/sharding regardless of
        the topology that wrote the checkpoint (universal-checkpoint semantics).

        Integrity: the tag's SHA-256 manifest is validated before anything is
        restored (``CheckpointCorruptionError`` names the offending shard);
        ``tag=None`` resolves via ``latest`` with automatic fallback to the
        newest *committed* tag when the pointer is torn."""
        resolved = self._resolve_load_tag(load_dir, tag)
        if resolved is None:
            logger.warning(f"No '{LATEST_FILE}' file at {load_dir} and no "
                           "committed tags found; nothing loaded")
            return None, {}
        tag = resolved
        path = os.path.join(load_dir, str(tag))
        if not os.path.isdir(path):
            raise CheckpointCorruptionError(
                f"checkpoint tag {tag!r} does not exist under {load_dir}")
        if validate:
            validate_manifest(path)
        fault_point("ckpt.load.begin")
        if self.param_offload_enabled:
            self._param_offload.load_from(
                self.checkpoint_engine, os.path.join(path, "offload_state"),
                load_optimizer_states=(load_optimizer_states
                                       and not load_module_only))
            side = self.checkpoint_engine.load(os.path.join(path, "client_state.pkl"))
            self._host_steps = side.get("global_step", 0)
            self.micro_steps = side.get("micro_steps", 0)
            self._param_offload._skipped_steps = side.get("skipped_steps", 0)
            # QAT schedule gating resumes where training left off (push_step is
            # the coordinator's train-step mirror)
            self._param_offload.push_step = self._host_steps
            if self.curriculum_scheduler is not None:
                self.curriculum_scheduler.update_difficulty(self._host_steps)
            if self.progressive_layer_drop is not None:
                self.progressive_layer_drop.update_state(self._host_steps)
            if load_lr_scheduler_states and self.lr_scheduler is not None \
                    and side.get("lr_scheduler") is not None:
                self.lr_scheduler.load_state_dict(side["lr_scheduler"])
            log_dist(f"loaded param-offload checkpoint {path} at "
                     f"global_step={self._host_steps}", ranks=[0])
            return path, side.get("client_state", {})
        restored = self.checkpoint_engine.load(
            os.path.join(path, "state"),
            template=self.state._asdict(),
            shardings=self._state_shardings._asdict())
        new_state = TrainState(**restored)
        if load_module_only or not load_optimizer_states:
            new_state = self.state._replace(params=new_state.params,
                                            global_step=new_state.global_step)
        self.state = new_state
        if getattr(self, "_qar_residual", None) is not None:
            # EF residual is per-worker transient state, not checkpointed —
            # restart from zero feedback (one step of extra quantization noise)
            self._qar_residual, self._qar_shardings = self._init_qar_residual()
        if self.offload_enabled:
            off_path = os.path.join(path, "offload_state")
            if load_optimizer_states and not load_module_only \
                    and self._offload_tier.has_checkpoint(off_path):
                self._offload_tier.load_from(self.checkpoint_engine, off_path)
                # device params re-derive from the restored masters (they are the source
                # of truth in offload mode)
                self.state = self.state._replace(
                    params=self._offload_tier.initial_device_params())
            else:
                # module-only / no-opt-state load (or a checkpoint written without the
                # offload tier): masters MUST follow the loaded weights, else the next
                # host step would overwrite them with stale init-time masters
                self._offload_tier.reseed_from_device(self.state.params)
        self._host_steps = int(new_state.global_step)   # resync host mirror (one-off sync)
        if self.curriculum_scheduler is not None:
            # fast-forward difficulty to the resumed step (custom schedules aside,
            # difficulty is a pure function of the step)
            self.curriculum_scheduler.update_difficulty(self._host_steps)
        if self.progressive_layer_drop is not None:
            # theta is likewise a pure function of the step
            self.progressive_layer_drop.update_state(self._host_steps)
        side = self.checkpoint_engine.load(os.path.join(path, "client_state.pkl"))
        self.micro_steps = side.get("micro_steps", 0)
        if load_lr_scheduler_states and self.lr_scheduler is not None \
                and side.get("lr_scheduler") is not None:
            self.lr_scheduler.load_state_dict(side["lr_scheduler"])
        client_state = side.get("client_state", {})
        log_dist(f"loaded checkpoint {path} at global_step={self.global_steps}", ranks=[0])
        return path, client_state


class CheckpointAutoSaver:
    """Preemption-aware automatic checkpointing around a :class:`DeepSpeedEngine`.

    Two triggers (reference: megatron-style ``--save-interval`` + the launcher's
    SIGTERM propagation discipline):

    - every ``interval_steps`` optimizer steps, ``after_step()`` saves a tag;
    - on SIGTERM (scheduler preemption) the handler only sets a flag — the save
      happens at the next ``after_step()`` call, i.e. at a step boundary where
      the engine state is consistent — then a ``preempted`` marker naming the
      tag is written and ``SystemExit(128+SIGTERM)`` is raised so the launcher /
      scheduler restarts the job, which resumes via ``resume()``.

    Usage::

        saver = CheckpointAutoSaver(engine, save_dir, interval_steps=100)
        saver.resume()                     # load latest committed tag, if any
        with saver:                        # installs the SIGTERM handler
            for batch in data:
                engine.train_batch(batch)
                saver.after_step()
    """

    PREEMPT_MARKER = "preempted"

    def __init__(self, engine, save_dir: str, interval_steps: int = 0,
                 tag_prefix: str = "global_step", exit_on_preempt: bool = True,
                 client_state_fn: Optional[Callable[[], dict]] = None):
        self.engine = engine
        self.save_dir = save_dir
        self.interval_steps = int(interval_steps)
        self.tag_prefix = tag_prefix
        self.exit_on_preempt = exit_on_preempt
        self.client_state_fn = client_state_fn
        self._preempt = threading.Event()
        self._prev_handler = None
        self._installed = False
        self.last_saved_tag: Optional[str] = None

    # ------------------------------------------------------------- signal wiring
    def install(self) -> "CheckpointAutoSaver":
        """Install the SIGTERM handler (main thread only — a no-op flag set, so
        it is safe inside any training loop)."""
        self._prev_handler = signal.signal(signal.SIGTERM, self._on_sigterm)
        self._installed = True
        return self

    def uninstall(self):
        if self._installed:
            signal.signal(signal.SIGTERM, self._prev_handler or signal.SIG_DFL)
            self._installed = False

    def __enter__(self) -> "CheckpointAutoSaver":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def _on_sigterm(self, signum, frame):
        logger.warning("[autosave] SIGTERM received — checkpoint at next step "
                       "boundary, then exit for scheduler restart")
        self._preempt.set()

    @property
    def preempted(self) -> bool:
        return self._preempt.is_set()

    # ------------------------------------------------------------------- saving
    def save(self, mark_preempted: bool = False) -> str:
        tag = f"{self.tag_prefix}{self.engine.global_steps}"
        client_state = self.client_state_fn() if self.client_state_fn else None
        path = self.engine.save_checkpoint(self.save_dir, tag=tag,
                                           client_state=client_state)
        self.last_saved_tag = tag
        if mark_preempted and dist.get_rank() == 0:
            marker = os.path.join(self.save_dir, self.PREEMPT_MARKER)
            with open(marker + ".tmp", "w") as f:
                f.write(tag)
            os.rename(marker + ".tmp", marker)
        return path

    def after_step(self) -> Optional[str]:
        """Call once per optimizer step. Saves when the interval elapses or a
        preemption is pending; on preemption also exits (``exit_on_preempt``).
        Returns the saved path, or None when no save was due.

        Multi-host: ranks can observe SIGTERM on different step boundaries, so
        the flag is agreed via a max-allreduce each step — every rank then
        enters the collective save at the SAME step (mismatched steps would
        deadlock the save barriers)."""
        preempted = self._preempt.is_set()
        if dist.get_world_size() > 1:
            agreed = dist.all_reduce(np.asarray(int(preempted), np.int32),
                                     op="max")
            if bool(agreed) and not preempted:
                self._preempt.set()
            preempted = bool(agreed)
        if preempted:
            path = self.save(mark_preempted=True)
            if self.exit_on_preempt:
                raise SystemExit(128 + signal.SIGTERM)
            self._preempt.clear()
            return path
        steps = self.engine.global_steps
        if self.interval_steps > 0 and steps > 0 \
                and steps % self.interval_steps == 0 \
                and self.last_saved_tag != f"{self.tag_prefix}{steps}":
            return self.save()
        return None

    # ----------------------------------------------------------------- resuming
    def resume(self):
        """Load the newest committed checkpoint (via ``latest`` with torn-tag
        fallback) and clear any preemption marker. Returns
        ``(path, client_state)`` or ``(None, {})`` when nothing is saved yet."""
        path, client_state = self.engine.load_checkpoint(self.save_dir)
        marker = os.path.join(self.save_dir, self.PREEMPT_MARKER)
        if os.path.isfile(marker):
            if dist.get_rank() == 0:
                logger.info(f"[autosave] resuming after preemption "
                            f"(marker tag {open(marker).read().strip()!r})")
                os.unlink(marker)
        return path, client_state
