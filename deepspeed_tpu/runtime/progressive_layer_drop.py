"""Progressive Layer Dropping (PLD).

Behavioural equivalent of reference ``deepspeed/runtime/progressive_layer_drop.py``
(``ProgressiveLayerDrop``): the global keep-probability schedule
``theta(t) = (1 - theta) * exp(-gamma * t) + theta`` from Zhang & He 2020
(arXiv:2010.13369), plus the depth-dependent per-layer keep probability and a jit-safe
stochastic-depth wrapper (the reference threads ``pld_theta`` into its transformer
kernel; here the model applies :func:`layer_drop` around each block).
"""

import math
from typing import Callable

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})",
                 ranks=[0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self) -> float:
        return self.current_theta

    def update_state(self, global_step: int) -> float:
        self.current_theta = (1.0 - self.theta) * math.exp(
            -self.gamma * global_step) + self.theta
        return self.current_theta


def keep_prob(theta, layer_idx: int, num_layers: int):
    """Depth-scaled keep probability: deeper layers drop more
    (PLD paper eq. 6: ``p_l = 1 - l/L * (1 - theta)``)."""
    frac = (layer_idx + 1) / num_layers
    return 1.0 - frac * (1.0 - theta)


def layer_drop(layer_fn: Callable, x, rng, theta, layer_idx: int,
               num_layers: int):
    """Stochastic-depth wrapper: with prob ``1 - p_l`` the block becomes identity
    (residual passthrough); outputs are scaled by ``1/p_l`` when kept so the forward
    is unbiased. Jit-safe: the draw is a where-select, no recompilation as theta
    anneals (pass theta as a traced scalar)."""
    p = jnp.asarray(keep_prob(theta, layer_idx, num_layers), jnp.float32)
    keep = jax.random.bernoulli(rng, p)
    y = layer_fn(x)
    return jnp.where(keep, x + (y - x) / p, x)
