"""Learning-rate schedules.

Behavioural equivalents of reference ``deepspeed/runtime/lr_schedules.py``:
``LRRangeTest:308``, ``OneCycle:415``, ``WarmupLR:704``, ``WarmupDecayLR:800``.

Each schedule is a host-side object with the reference's ``step()/get_lr()/state_dict()``
surface; the engine feeds the resulting scalar into the jitted train step as a traced argument,
so stepping the schedule never recompiles.
"""

import math
from typing import List, Optional, Union

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]


class _Schedule:
    """Common step/state plumbing (mirrors torch scheduler surface the reference exposes)."""

    def __init__(self, last_batch_iteration: int = -1):
        self.last_batch_iteration = last_batch_iteration
        self._last_lr: List[float] = [0.0]

    def get_lr(self) -> List[float]:
        raise NotImplementedError

    def step(self, last_batch_iteration: Optional[int] = None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = self.get_lr()

    def get_last_lr(self) -> List[float]:
        return list(self._last_lr)

    @property
    def lr(self) -> float:
        if self.last_batch_iteration < 0:
            self.last_batch_iteration = 0
            out = self.get_lr()[0]
            self.last_batch_iteration = -1
            return out
        return self.get_lr()[0]

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_Schedule):
    """Reference ``lr_schedules.py:308`` — linear/continuous LR sweep for range tests."""

    def __init__(self, optimizer=None, lr_range_test_min_lr: Union[float, List[float]] = 1e-3,
                 lr_range_test_step_size: int = 2000, lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False, last_batch_iteration: int = -1):
        super().__init__(last_batch_iteration)
        self.min_lr = (lr_range_test_min_lr if isinstance(lr_range_test_min_lr, (int, float))
                       else lr_range_test_min_lr[0])
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def get_lr(self) -> List[float]:
        it = max(self.last_batch_iteration, 0)
        if self.staircase:
            interval = float(it // self.step_size)
        else:
            interval = it / self.step_size
        return [self.min_lr * (1 + self.step_rate * interval)]


class OneCycle(_Schedule):
    """Reference ``lr_schedules.py:415`` — 1cycle policy (cycle up, down, then decay)."""

    def __init__(self, optimizer=None, cycle_min_lr: float = 1e-4, cycle_max_lr: float = 1e-3,
                 decay_lr_rate: float = 0.0, cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0, cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0, cycle_momentum: bool = True,
                 cycle_min_mom: float = 0.8, cycle_max_mom: float = 0.9,
                 decay_mom_rate: float = 0.0, last_batch_iteration: int = -1):
        super().__init__(last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_size = cycle_first_step_size
        self.second_size = (cycle_second_step_size if cycle_second_step_size is not None
                            else cycle_first_step_size)
        self.decay_step_size = decay_step_size
        self.total_cycle = self.first_size + self.second_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def get_lr(self) -> List[float]:
        it = max(self.last_batch_iteration, 0)
        if it <= self.total_cycle:
            if it <= self.first_size:
                frac = it / self.first_size
                lr = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * frac
            else:
                frac = (it - self.first_size) / self.second_size
                lr = self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * frac
            return [lr]
        # decay phase
        decay_steps = it - self.total_cycle
        if self.decay_step_size > 0:
            intervals = decay_steps / self.decay_step_size
        else:
            intervals = decay_steps
        return [self.cycle_min_lr / (1.0 + self.decay_lr_rate * intervals)]

    def get_mom(self) -> List[float]:
        it = max(self.last_batch_iteration, 0)
        if not self.cycle_momentum or it > self.total_cycle:
            return [self.cycle_min_mom]
        if it <= self.first_size:
            frac = it / self.first_size
            return [self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * frac]
        frac = (it - self.first_size) / self.second_size
        return [self.cycle_min_mom + (self.cycle_max_mom - self.cycle_min_mom) * frac]


class WarmupLR(_Schedule):
    """Reference ``lr_schedules.py:704`` — warm up then hold."""

    def __init__(self, optimizer=None, warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000, warmup_type: str = "log",
                 last_batch_iteration: int = -1):
        super().__init__(last_batch_iteration)
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        if not (warmup_type in ("log", "linear")):
            raise AssertionError('warmup_type in ("log", "linear")')
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _warmup_gamma(self, it: int) -> float:
        if it < self.warmup_num_steps:
            if self.warmup_type == "log":
                return self.inverse_log_warm_up * math.log(it + 1)
            return it / self.warmup_num_steps
        return 1.0

    def get_lr(self) -> List[float]:
        it = max(self.last_batch_iteration, 0)
        gamma = self._warmup_gamma(it)
        return [self.min_lr + (self.max_lr - self.min_lr) * gamma]


class WarmupDecayLR(WarmupLR):
    """Reference ``lr_schedules.py:800`` — warm up then linear decay to zero."""

    def __init__(self, optimizer=None, total_num_steps: int = 10000, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 warmup_type: str = "log", last_batch_iteration: int = -1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)
        if self.total_num_steps < self.warmup_num_steps:
            from ..utils.logging import logger
            logger.warning(f"total_num_steps {total_num_steps} is less than "
                           f"warmup_num_steps {warmup_num_steps}")

    def _warmup_gamma(self, it: int) -> float:
        if it < self.warmup_num_steps:
            return super()._warmup_gamma(it)
        return max(0.0, (self.total_num_steps - it) /
                   max(1, self.total_num_steps - self.warmup_num_steps))


SCHEDULE_REGISTRY = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
}


def get_lr_scheduler(name: str, params: dict, optimizer=None) -> _Schedule:
    if name not in SCHEDULE_REGISTRY:
        raise ValueError(f"Unknown LR schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_REGISTRY[name](optimizer=optimizer, **params)
