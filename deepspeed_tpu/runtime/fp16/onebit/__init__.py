"""1-bit optimizers (reference deepspeed/runtime/fp16/onebit)."""
from .adam import onebit_adam, zero_one_adam
from .lamb import onebit_lamb
