"""1-bit Adam and 0/1 Adam.

Behavioural equivalents of reference ``deepspeed/runtime/fp16/onebit/adam.py``
(``OnebitAdam:11``) and ``zoadam.py`` (``ZeroOneAdam``):

- **1-bit Adam**: plain Adam for ``freeze_step`` warmup steps; afterwards the variance
  ``v`` is FROZEN and only the momentum is exchanged, sign-compressed with error
  feedback (compression stage). Convergence matches Adam at ~1/32 the comm volume
  (Tang et al., 2021).
- **0/1 Adam**: generalises with learning-rate-freeze + adaptive variance-update
  intervals (``var_update_policy``), here the interval schedule
  ``var_freeze_step``/``var_update_scaler``.

TPU mapping: the engine's gradients arrive as the *global mean* (XLA reduces them as
part of the sharded backward), so the momentum compression here applies
``C(m) = sign(m+e)·E|m+e|`` with persistent error feedback ``e`` — numerically the
single-controller view of the reference's compressed allreduce (whose per-worker
residuals live on each rank). The wire-level 1-bit collective for explicit
``shard_map`` pipelines is :func:`deepspeed_tpu.comm.compressed.compressed_allreduce`.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ....ops.optimizer import Optimizer


class OnebitAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    error: any          # error-feedback residual per param (compression stage)


def _sign_compress(m, error):
    """Error-compensated 1-bit form — the unpacked core of
    ``comm.compressed.compress_signs`` (which adds the wire bit-packing)."""
    from ....comm.compressed import sign_compress
    return sign_compress(m, error)


def onebit_adam(betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                weight_decay: float = 0.0, freeze_step: int = 100,
                adam_w_mode: bool = False) -> Optimizer:
    """Reference ``OnebitAdam.__init__`` defaults; ``freeze_step`` gates the warmup →
    compression transition (traced: no recompile at the boundary)."""
    beta1, beta2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return OnebitAdamState(
            step=jnp.int32(0),
            exp_avg=jax.tree_util.tree_map(zeros, params),
            exp_avg_sq=jax.tree_util.tree_map(zeros, params),
            error=jax.tree_util.tree_map(zeros, params),
        )

    def update(grads, state: OnebitAdamState, params, lr):
        step = state.step + 1
        frozen = step > freeze_step
        bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
        bc2 = 1.0 - beta2 ** step.astype(jnp.float32)

        def upd(p, g, m, v, e):
            g = g.astype(jnp.float32)
            if weight_decay != 0.0 and not adam_w_mode:
                g = g + weight_decay * p.astype(jnp.float32)
            m_raw = beta1 * m + (1.0 - beta1) * g
            # compression stage: momentum replaced by its 1-bit form + error feedback
            m_comp, e_new = _sign_compress(m_raw, e)
            m_new = jnp.where(frozen, m_comp, m_raw)
            e_out = jnp.where(frozen, e_new, e)
            # variance frozen after warmup (the 1-bit Adam invariant)
            v_new = jnp.where(frozen, v, beta2 * v + (1.0 - beta2) * g * g)
            denom = jnp.sqrt(v_new / bc2) + eps
            delta = (m_new / bc1) / denom
            if weight_decay != 0.0 and adam_w_mode:
                delta = delta + weight_decay * p.astype(jnp.float32)
            return (p.astype(jnp.float32) - lr * delta).astype(p.dtype), \
                m_new, v_new, e_out

        out = jax.tree_util.tree_map(upd, params, grads, state.exp_avg,
                                     state.exp_avg_sq, state.error)
        pick = lambda i: jax.tree_util.tree_map(
            lambda t: t[i], out, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), OnebitAdamState(step=step, exp_avg=pick(1),
                                        exp_avg_sq=pick(2), error=pick(3))

    return Optimizer(init=init, update=update, name="OnebitAdam")


class ZeroOneAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    error: any
    last_var_update: jnp.ndarray   # step of the most recent variance refresh
    var_interval: jnp.ndarray      # current interval between refreshes


def zero_one_adam(betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                  weight_decay: float = 0.0,
                  var_freeze_step: int = 100000,
                  var_update_scaler: int = 16,
                  adam_w_mode: bool = False) -> Optimizer:
    """0/1 Adam (reference ``zoadam.py:ZeroOneAdam``): variance refreshed only at
    exponentially-spaced intervals (``var_update_scaler``) until ``var_freeze_step``,
    momentum always 1-bit-compressed with error feedback.

    The reference's ``local_step_scaler``/``local_step_clipper`` knobs schedule how
    often workers SYNC at all (local-update mode over the wire); in this
    single-controller in-graph optimizer every step is globally consistent, so those
    knobs have no meaning and are deliberately not accepted.
    """
    beta1, beta2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return ZeroOneAdamState(
            step=jnp.int32(0),
            exp_avg=jax.tree_util.tree_map(zeros, params),
            exp_avg_sq=jax.tree_util.tree_map(zeros, params),
            error=jax.tree_util.tree_map(zeros, params),
            last_var_update=jnp.int32(0),
            var_interval=jnp.int32(1),
        )

    def update(grads, state: ZeroOneAdamState, params, lr):
        step = state.step + 1
        bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
        bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        refresh = jnp.logical_and(
            step - state.last_var_update >= state.var_interval,
            step <= var_freeze_step)
        new_interval = jnp.where(
            refresh,
            jnp.minimum(state.var_interval * 2,
                        jnp.int32(var_update_scaler)),
            state.var_interval)
        new_last = jnp.where(refresh, step, state.last_var_update)

        def upd(p, g, m, v, e):
            g = g.astype(jnp.float32)
            if weight_decay != 0.0 and not adam_w_mode:
                g = g + weight_decay * p.astype(jnp.float32)
            m_raw = beta1 * m + (1.0 - beta1) * g
            m_new, e_new = _sign_compress(m_raw, e)
            v_new = jnp.where(refresh, beta2 * v + (1.0 - beta2) * g * g, v)
            denom = jnp.sqrt(v_new / bc2) + eps
            delta = (m_new / bc1) / denom
            if weight_decay != 0.0 and adam_w_mode:
                delta = delta + weight_decay * p.astype(jnp.float32)
            return (p.astype(jnp.float32) - lr * delta).astype(p.dtype), \
                m_new, v_new, e_new

        out = jax.tree_util.tree_map(upd, params, grads, state.exp_avg,
                                     state.exp_avg_sq, state.error)
        pick = lambda i: jax.tree_util.tree_map(
            lambda t: t[i], out, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), ZeroOneAdamState(
            step=step, exp_avg=pick(1), exp_avg_sq=pick(2), error=pick(3),
            last_var_update=new_last, var_interval=new_interval)

    return Optimizer(init=init, update=update, name="ZeroOneAdam")
