"""1-bit LAMB.

Behavioural equivalent of reference ``deepspeed/runtime/fp16/onebit/lamb.py``
(``OnebitLamb``, Li et al. 2021): plain LAMB for ``freeze_step`` warmup steps; in the
compression stage the variance AND the per-tensor LAMB scaling are FROZEN (the trust
ratio recorded at the freeze boundary keeps steering step sizes) while the momentum is
1-bit sign-compressed with error feedback — the property that makes layerwise adaptive
rates survive compressed communication.

Same single-controller mapping as :mod:`.adam`: compression applies to the global
momentum with a persistent error residual; the wire-level collective for explicit
shard_map pipelines is ``comm.compressed.compressed_allreduce``.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ....ops.optimizer import Optimizer
from .adam import _sign_compress


class OnebitLambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    error: any
    frozen_trust: any       # per-tensor trust ratio recorded at the freeze boundary


def onebit_lamb(betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                weight_decay: float = 0.0, freeze_step: int = 100,
                max_coeff: float = 10.0, min_coeff: float = 0.01) -> Optimizer:
    beta1, beta2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return OnebitLambState(
            step=jnp.int32(0),
            exp_avg=jax.tree_util.tree_map(zeros, params),
            exp_avg_sq=jax.tree_util.tree_map(zeros, params),
            error=jax.tree_util.tree_map(zeros, params),
            frozen_trust=jax.tree_util.tree_map(
                lambda p: jnp.float32(1.0), params),
        )

    def update(grads, state: OnebitLambState, params, lr):
        step = state.step + 1
        frozen = step > freeze_step
        at_boundary = step == freeze_step
        bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
        bc2 = 1.0 - beta2 ** step.astype(jnp.float32)

        def upd(p, g, m, v, e, tr):
            g = g.astype(jnp.float32)
            m_raw = beta1 * m + (1.0 - beta1) * g
            m_comp, e_new = _sign_compress(m_raw, e)
            m_new = jnp.where(frozen, m_comp, m_raw)
            e_out = jnp.where(frozen, e_new, e)
            v_new = jnp.where(frozen, v, beta2 * v + (1.0 - beta2) * g * g)
            u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if weight_decay != 0.0:
                u = u + weight_decay * p.astype(jnp.float32)
            p_norm = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
            u_norm = jnp.linalg.norm(u.reshape(-1))
            live_trust = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
            live_trust = jnp.where(p_norm > 0, live_trust, 1.0)
            live_trust = jnp.clip(live_trust, min_coeff, max_coeff)
            # record the ratio at the boundary; afterwards keep steering with it
            # (the reference's frozen lamb_coeffs)
            tr_new = jnp.where(at_boundary, live_trust, tr)
            trust = jnp.where(frozen, tr_new, live_trust)
            return (p - lr * trust * u).astype(p.dtype), m_new, v_new, e_out, tr_new

        out = jax.tree_util.tree_map(upd, params, grads, state.exp_avg,
                                     state.exp_avg_sq, state.error,
                                     state.frozen_trust)
        pick = lambda i: jax.tree_util.tree_map(
            lambda t: t[i], out, is_leaf=lambda t: isinstance(t, tuple))
        return pick(0), OnebitLambState(step=step, exp_avg=pick(1),
                                        exp_avg_sq=pick(2), error=pick(3),
                                        frozen_trust=pick(4))

    return Optimizer(init=init, update=update, name="OnebitLamb")
