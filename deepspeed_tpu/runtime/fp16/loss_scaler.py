"""Static and dynamic loss scaling — functional, jit-compatible.

Behavioural equivalent of reference ``deepspeed/runtime/fp16/loss_scaler.py``
(``LossScaler:59``, ``DynamicLossScaler:82``): scale the loss before differentiation so fp16
gradients don't underflow; on overflow skip the step and halve the scale (respecting
hysteresis); after ``scale_window`` clean steps double it.

Unlike the reference's stateful object mutated between autograd calls, the scaler state here is
a pytree threaded through the jitted train step, updated with ``lax.cond``-free arithmetic so it
lives entirely on device.
"""

from typing import NamedTuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    cur_scale: jnp.ndarray       # f32 scalar
    cur_hysteresis: jnp.ndarray  # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    iteration: jnp.ndarray       # i32 scalar


def make_static_state(scale: float) -> LossScaleState:
    return LossScaleState(
        cur_scale=jnp.float32(scale),
        cur_hysteresis=jnp.int32(1),
        last_overflow_iter=jnp.int32(-1),
        iteration=jnp.int32(0),
    )


class DynamicLossScaler:
    """Pure update rules over :class:`LossScaleState`.

    Reference defaults mirror ``fp16/loss_scaler.py:82`` (init 2**32 there; DeepSpeed's engine
    uses ``initial_scale_power`` from config, default 2**16).
    """

    def __init__(self, init_scale: float = 2.0**16, scale_factor: float = 2.0,
                 scale_window: int = 1000, min_scale: float = 1.0, delayed_shift: int = 1,
                 consecutive_hysteresis: bool = False):
        self.init_scale = init_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.delayed_shift = delayed_shift  # "hysteresis" in config
        self.consecutive_hysteresis = consecutive_hysteresis

    def init_state(self) -> LossScaleState:
        return LossScaleState(
            cur_scale=jnp.float32(self.init_scale),
            cur_hysteresis=jnp.int32(self.delayed_shift),
            last_overflow_iter=jnp.int32(-1),
            iteration=jnp.int32(0),
        )

    def update(self, state: LossScaleState, overflow: jnp.ndarray) -> LossScaleState:
        """One iteration's scale update. ``overflow`` is a traced bool scalar."""
        it = state.iteration
        # --- overflow branch ------------------------------------------------
        hysteresis_exhausted = state.cur_hysteresis <= 1
        dec_scale = jnp.maximum(state.cur_scale / self.scale_factor, self.min_scale)
        of_scale = jnp.where(hysteresis_exhausted, dec_scale, state.cur_scale)
        of_hyst = jnp.where(hysteresis_exhausted, state.cur_hysteresis,
                            state.cur_hysteresis - 1)
        # --- clean branch ---------------------------------------------------
        # growth when scale_window clean iterations have passed since the last overflow
        # (reference fp16/loss_scaler.py: (cur_iter - last_overflow_iter) % window == 0)
        window_done = (it - state.last_overflow_iter) % self.scale_window == 0
        ok_scale = jnp.where(window_done, state.cur_scale * self.scale_factor,
                             state.cur_scale)
        ok_hyst = (jnp.int32(self.delayed_shift) if self.consecutive_hysteresis
                   else state.cur_hysteresis)
        return LossScaleState(
            cur_scale=jnp.where(overflow, of_scale, ok_scale),
            cur_hysteresis=jnp.where(overflow, of_hyst, ok_hyst).astype(jnp.int32),
            last_overflow_iter=jnp.where(overflow, it, state.last_overflow_iter),
            iteration=it + 1,
        )


def create_loss_scaler(fp16_config) -> "tuple[DynamicLossScaler, LossScaleState]":
    """Build scaler + initial state from an ``FP16Config`` (dynamic iff loss_scale == 0)."""
    if not fp16_config.enabled:
        scaler = DynamicLossScaler(init_scale=1.0, scale_window=10**9, min_scale=1.0)
        return scaler, make_static_state(1.0)
    if fp16_config.dynamic:
        scaler = DynamicLossScaler(
            init_scale=2.0**fp16_config.initial_scale_power,
            scale_window=fp16_config.loss_scale_window,
            min_scale=fp16_config.min_loss_scale,
            delayed_shift=fp16_config.hysteresis,
        )
        return scaler, scaler.init_state()
    scaler = DynamicLossScaler(init_scale=fp16_config.loss_scale, scale_window=10**9,
                               min_scale=fp16_config.loss_scale)
    return scaler, make_static_state(fp16_config.loss_scale)
