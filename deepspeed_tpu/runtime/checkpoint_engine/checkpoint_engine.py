"""Pluggable checkpoint backends with crash-consistent commits.

Behavioural equivalent of reference ``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py``
(``CheckpointEngine`` ABC) + ``torch_checkpoint_engine.py`` + ``nebula_checkpoint_engine.py``.
The default backend is Orbax/TensorStore, which natively writes *sharded, re-shardable* arrays —
this is what makes every checkpoint a "universal checkpoint" (reference
``checkpoint/universal_checkpoint.py``) by construction: restore may specify any sharding/mesh.

Commit protocol (crash consistency — see ``docs/FAULT_TOLERANCE.md``):

1. all tag data is staged into ``<save_dir>/<tag>.tmp/`` (``begin_tag``);
2. ``commit_tag`` drains async writes, computes a per-file SHA-256 manifest
   (``manifest.json``), fsyncs every staged file, and publishes the tag with a
   single ``os.rename(<tag>.tmp, <tag>)`` + parent-dir fsync;
3. the ``latest`` pointer is written (atomically, by the engine) only after the
   rename lands.

A kill at ANY point leaves either the previous committed tag intact (tmp dir
is garbage, ignored and reclaimed) or the new tag fully visible. ``load``
validates the manifest and raises :class:`CheckpointCorruptionError` naming the
first offending file; :func:`find_latest_committed_tag` falls back to the newest
tag whose manifest validates when the ``latest`` pointer is torn or stale.
"""

import hashlib
import json
import os
import pickle
import shutil
import time
from typing import Any, Dict, List, Optional

from ...utils.fault_injection import fault_point, retry_with_backoff
from ...utils.logging import logger

TMP_SUFFIX = ".tmp"
OLD_SUFFIX = ".old"       # graveyard for a re-saved tag's previous directory
MANIFEST_FILE = "manifest.json"
LATEST_FILE = "latest"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed manifest/checksum validation; the message names the
    offending file and the failure mode (missing / size / digest)."""


def _fsync_file(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse dir fsync; the rename is still ordered
    finally:
        os.close(fd)


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            blk = f.read(chunk)
            if not blk:
                break
            h.update(blk)
    return h.hexdigest()


def _walk_files(root: str) -> List[str]:
    """Relative paths of every regular file under ``root`` (sorted, manifest
    excluded)."""
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            rel = os.path.relpath(os.path.join(dirpath, name), root)
            if rel != MANIFEST_FILE:
                out.append(rel)
    return sorted(out)


def write_manifest(tag_dir: str, tag: str, fsync: bool = True) -> Dict[str, Any]:
    """Per-shard SHA-256 manifest over every file in ``tag_dir``. Written last
    (its presence marks a complete data set) and atomically (tmp + rename)."""
    files = {}
    for rel in _walk_files(tag_dir):
        full = os.path.join(tag_dir, rel)
        fault_point("ckpt.manifest.hash")
        files[rel] = {"sha256": _sha256_file(full),
                      "size": os.path.getsize(full)}
        if fsync:
            _fsync_file(full)
    manifest = {"version": 1, "tag": str(tag), "files": files,
                "committed_at": time.time()}
    tmp = os.path.join(tag_dir, MANIFEST_FILE + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.rename(tmp, os.path.join(tag_dir, MANIFEST_FILE))
    if fsync:
        _fsync_dir(tag_dir)
    return manifest


def validate_manifest(tag_dir: str, strict: bool = False):
    """Validate every file in ``tag_dir`` against its manifest.

    Raises :class:`CheckpointCorruptionError` on a missing/truncated/corrupt
    file (named in the message). A missing manifest is tolerated with a warning
    (pre-manifest checkpoints) unless ``strict``.
    """
    mpath = os.path.join(tag_dir, MANIFEST_FILE)
    if not os.path.isfile(mpath):
        if strict:
            raise CheckpointCorruptionError(
                f"checkpoint {tag_dir} has no {MANIFEST_FILE} — it was never "
                "committed (torn write?)")
        logger.warning(f"[ckpt] {tag_dir} has no {MANIFEST_FILE}; skipping "
                       "integrity validation (pre-manifest checkpoint?)")
        return
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptionError(
            f"checkpoint manifest {mpath} is unreadable: {e}") from e
    for rel, meta in manifest.get("files", {}).items():
        full = os.path.join(tag_dir, rel)
        if not os.path.isfile(full):
            raise CheckpointCorruptionError(
                f"checkpoint {tag_dir}: shard {rel!r} is missing")
        size = os.path.getsize(full)
        if size != meta["size"]:
            raise CheckpointCorruptionError(
                f"checkpoint {tag_dir}: shard {rel!r} truncated "
                f"({size} bytes, manifest says {meta['size']})")
        if _sha256_file(full) != meta["sha256"]:
            raise CheckpointCorruptionError(
                f"checkpoint {tag_dir}: shard {rel!r} failed its SHA-256 "
                "checksum — the file is corrupt")


def is_committed_tag(save_dir: str, tag: str) -> bool:
    """A tag is committed iff its final directory exists with a readable
    manifest (tmp staging dirs are by definition uncommitted)."""
    tag_dir = os.path.join(save_dir, str(tag))
    if not os.path.isdir(tag_dir) or str(tag).endswith(TMP_SUFFIX) \
            or str(tag).endswith(OLD_SUFFIX):
        return False
    mpath = os.path.join(tag_dir, MANIFEST_FILE)
    if not os.path.isfile(mpath):
        # pre-manifest checkpoint: committed if the dir simply exists
        return True
    try:
        with open(mpath) as f:
            json.load(f)
        return True
    except (OSError, ValueError):
        return False


def find_latest_committed_tag(save_dir: str,
                              exclude: Optional[str] = None) -> Optional[str]:
    """Newest committed tag under ``save_dir`` by manifest commit time (file
    mtime fallback), skipping ``exclude`` and staging dirs — the automatic
    fallback when the ``latest`` pointer names a torn checkpoint."""
    best, best_t = None, -1.0
    try:
        entries = os.listdir(save_dir)
    except OSError:
        return None
    for name in entries:
        if name == str(exclude) or name.endswith(TMP_SUFFIX) \
                or name.endswith(OLD_SUFFIX):
            continue
        tag_dir = os.path.join(save_dir, name)
        mpath = os.path.join(tag_dir, MANIFEST_FILE)
        if not os.path.isfile(mpath):
            continue
        try:
            with open(mpath) as f:
                t = float(json.load(f).get("committed_at", 0.0))
        except (OSError, ValueError):
            continue
        t = t or os.path.getmtime(mpath)
        if t > best_t:
            best, best_t = name, t
    return best


def write_latest_pointer(save_dir: str, tag: str):
    """Atomic ``latest`` update: tmp + fsync + rename (a crash mid-update leaves
    the previous pointer intact)."""
    fault_point("ckpt.latest")
    tmp = os.path.join(save_dir, LATEST_FILE + ".tmp")
    with open(tmp, "w") as f:
        f.write(str(tag))
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, os.path.join(save_dir, LATEST_FILE))
    _fsync_dir(save_dir)


class CheckpointEngine:
    """save/load/commit surface, mirroring the reference ABC, plus the atomic
    tag staging protocol (``begin_tag``/``commit_tag``)."""

    def __init__(self, config_params=None):
        self.config = config_params
        self._staging: Dict[str, str] = {}   # tag -> staged dir

    def create(self, tag: str):
        logger.info(f"[ckpt] start checkpoint {tag}")

    def save(self, state_dict: Any, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None, template: Any = None,
             shardings: Any = None) -> Any:
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        logger.info(f"[ckpt] checkpoint {tag} ready")
        return True

    def makedirs(self, path: str, exist_ok: bool = True):
        os.makedirs(path, exist_ok=exist_ok)

    # ------------------------------------------------------------ atomic tags
    def staging_path(self, save_dir: str, tag: str) -> str:
        """Where ``begin_tag`` stages this tag's data. Non-zero ranks of a
        multi-host save use this (plus ``makedirs``) instead of ``begin_tag`` —
        only ONE rank may run the stale-staging reclaim, or ranks racing
        through ``begin_tag`` would rmtree each other's in-flight writes."""
        return os.path.join(save_dir, f"{tag}{TMP_SUFFIX}")

    def begin_tag(self, save_dir: str, tag: str) -> str:
        """Open a staging directory ``<save_dir>/<tag>.tmp`` for this tag's data
        (leftover staging from a crashed save is reclaimed). Call on ONE rank;
        peers join via ``staging_path`` after a barrier."""
        os.makedirs(save_dir, exist_ok=True)
        staged = self.staging_path(save_dir, tag)
        if os.path.isdir(staged):
            logger.warning(f"[ckpt] reclaiming stale staging dir {staged} "
                           "(previous save died mid-write)")
            shutil.rmtree(staged, ignore_errors=True)
        # a crash during a re-save of this tag can strand its graveyard copy
        grave = os.path.join(save_dir, f"{tag}{OLD_SUFFIX}")
        if os.path.isdir(grave):
            logger.warning(f"[ckpt] reclaiming stale graveyard dir {grave}")
            shutil.rmtree(grave, ignore_errors=True)
        os.makedirs(staged, exist_ok=True)
        self._staging[str(tag)] = staged
        self.create(tag)
        return staged

    def commit_tag(self, save_dir: str, tag: str) -> str:
        """Drain async writes, manifest + fsync the staged data, and publish the
        tag with one atomic rename. Returns the final tag directory."""
        staged = self._staging.pop(str(tag), None)
        if staged is None:
            staged = self.staging_path(save_dir, tag)
        if not os.path.isdir(staged):
            raise FileNotFoundError(
                f"commit_tag({tag!r}): no staged checkpoint at {staged} — "
                "begin_tag was never called or the staging dir was removed")
        # backend drain barrier (async orbax writes land before hashing)
        self.commit(tag)
        fault_point("ckpt.commit.manifest")
        write_manifest(staged, tag)
        final = os.path.join(save_dir, str(tag))
        if os.path.isdir(final):
            # re-saving an existing tag: replace it atomically-ish (rename to a
            # graveyard first so readers never see a half-deleted tag; a stale
            # graveyard left by a crash here is reclaimed by the next begin_tag
            # and ignored by tag discovery)
            grave = final + OLD_SUFFIX
            shutil.rmtree(grave, ignore_errors=True)
            os.rename(final, grave)
            shutil.rmtree(grave, ignore_errors=True)
        fault_point("ckpt.commit.rename")
        os.rename(staged, final)
        _fsync_dir(save_dir)
        logger.info(f"[ckpt] committed {tag} -> {final}")
        return final


class OrbaxCheckpointEngine(CheckpointEngine):
    """Array trees via Orbax (sharded + re-shardable); side metadata via JSON/pickle.

    ``save``/``load`` paths ending in ``.pkl``/``.json`` handle host-side state (scheduler,
    client state); other paths are treated as Orbax pytree directories. All writes
    go through :func:`retry_with_backoff` so transient I/O errors (flaky NFS/GCS
    fuse mounts) don't kill a training step that could have succeeded.
    """

    # transient-I/O retry policy (checkpoint writes are idempotent: orbax
    # force-overwrites and json/pkl rewrite whole files)
    IO_RETRIES = 2
    IO_BASE_DELAY = 0.05

    def __init__(self, config_params=None, use_async: bool = False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.use_async = use_async
        self._ckptr = ocp.StandardCheckpointer()

    def _retry(self, fn):
        return retry_with_backoff(fn, retries=self.IO_RETRIES,
                                  base_delay=self.IO_BASE_DELAY,
                                  retryable=(OSError,))

    def save(self, state_dict: Any, path: str):
        fault_point("ckpt.save")
        if path.endswith(".json"):
            def write_json():
                fault_point("ckpt.save.io")
                with open(path, "w") as f:
                    json.dump(state_dict, f, indent=2, default=str)
            self._retry(write_json)
            return
        if path.endswith(".pkl"):
            def write_pkl():
                fault_point("ckpt.save.io")
                with open(path, "wb") as f:
                    pickle.dump(state_dict, f)
            self._retry(write_pkl)
            return

        def write_tree():
            fault_point("ckpt.save.io")
            self._ckptr.save(os.path.abspath(path), state_dict, force=True)
            if not self.use_async:
                self._ckptr.wait_until_finished()
        self._retry(write_tree)
        # async_save: orbax's background thread drains the disk write while the
        # caller proceeds to the side-state writes/barrier; engine.save_checkpoint's
        # closing commit_tag() is the durability barrier, so the overlap is WITHIN
        # save_checkpoint (engine semantics require a durable checkpoint before
        # 'latest' advances — full resume-while-draining would defer commit to the
        # next save)

    def load(self, path: str, map_location=None, template: Any = None,
             shardings: Any = None) -> Any:
        fault_point("ckpt.load")
        if path.endswith(".json"):
            def read_json():
                fault_point("ckpt.load.io")
                with open(path) as f:
                    return json.load(f)
            return self._retry(read_json)
        if path.endswith(".pkl"):
            def read_pkl():
                fault_point("ckpt.load.io")
                with open(path, "rb") as f:
                    return pickle.load(f)
            return self._retry(read_pkl)
        import jax
        if template is not None:
            abstract = jax.tree_util.tree_map(
                lambda l, s=None: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                                       sharding=s) if hasattr(l, "shape") else l,
                template)
            if shardings is not None:
                abstract = jax.tree_util.tree_map(
                    lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s)
                    if hasattr(l, "shape") else l,
                    template, shardings)
            return self._retry(
                lambda: self._ckptr.restore(os.path.abspath(path), abstract))
        return self._retry(lambda: self._ckptr.restore(os.path.abspath(path)))

    def load_subtree(self, path: str, key: str, template: Any, shardings: Any = None):
        """Restore one top-level entry (e.g. just ``params``) from a full training
        checkpoint without materialising the rest (optimizer state etc.) — the inference
        engine's sharded-load path."""
        import jax
        ocp = self._ocp
        if shardings is not None:
            abstract = jax.tree_util.tree_map(
                lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s)
                if hasattr(l, "shape") else l, template, shardings)
        else:
            abstract = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype)
                if hasattr(l, "shape") else l, template)
        abspath = os.path.abspath(path)
        try:
            restore = self._ocp.args.PyTreeRestore(item={key: abstract},
                                                   partial_restore=True)
        except TypeError:
            # orbax < 0.9 has no partial_restore: restore the full tree with
            # the non-requested entries landed on one local device
            # (transiently costs their host RAM) and select the subtree
            meta = self._ckptr.metadata(abspath)
            meta_tree = dict(getattr(meta, "item_metadata", meta))
            host = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
            is_meta_leaf = lambda x: hasattr(x, "shape") and hasattr(x, "dtype")
            full = {
                k: (abstract if k == key else jax.tree_util.tree_map(
                    lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype,
                                                   sharding=host),
                    v, is_leaf=is_meta_leaf))
                for k, v in meta_tree.items()}
            restore = self._ocp.args.PyTreeRestore(item=full)
        with ocp.PyTreeCheckpointer() as ckptr:
            restored = ckptr.restore(abspath, args=restore)
        return restored[key]

    def commit(self, tag: str) -> bool:
        self._ckptr.wait_until_finished()
        return super().commit(tag)


def make_checkpoint_engine(checkpoint_config=None) -> CheckpointEngine:
    use_async = bool(getattr(checkpoint_config, "async_save", False))
    return OrbaxCheckpointEngine(checkpoint_config, use_async=use_async)
