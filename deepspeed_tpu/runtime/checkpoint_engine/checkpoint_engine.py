"""Pluggable checkpoint backends.

Behavioural equivalent of reference ``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py``
(``CheckpointEngine`` ABC) + ``torch_checkpoint_engine.py`` + ``nebula_checkpoint_engine.py``.
The default backend is Orbax/TensorStore, which natively writes *sharded, re-shardable* arrays —
this is what makes every checkpoint a "universal checkpoint" (reference
``checkpoint/universal_checkpoint.py``) by construction: restore may specify any sharding/mesh.
"""

import json
import os
import pickle
from typing import Any, Optional

from ...utils.logging import logger


class CheckpointEngine:
    """save/load/commit surface, mirroring the reference ABC."""

    def __init__(self, config_params=None):
        self.config = config_params

    def create(self, tag: str):
        logger.info(f"[ckpt] start checkpoint {tag}")

    def save(self, state_dict: Any, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None, template: Any = None,
             shardings: Any = None) -> Any:
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        logger.info(f"[ckpt] checkpoint {tag} ready")
        return True

    def makedirs(self, path: str, exist_ok: bool = True):
        os.makedirs(path, exist_ok=exist_ok)


class OrbaxCheckpointEngine(CheckpointEngine):
    """Array trees via Orbax (sharded + re-shardable); side metadata via JSON/pickle.

    ``save``/``load`` paths ending in ``.pkl``/``.json`` handle host-side state (scheduler,
    client state); other paths are treated as Orbax pytree directories.
    """

    def __init__(self, config_params=None, use_async: bool = False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.use_async = use_async
        self._ckptr = ocp.StandardCheckpointer()

    def save(self, state_dict: Any, path: str):
        if path.endswith(".json"):
            with open(path, "w") as f:
                json.dump(state_dict, f, indent=2, default=str)
            return
        if path.endswith(".pkl"):
            with open(path, "wb") as f:
                pickle.dump(state_dict, f)
            return
        self._ckptr.save(os.path.abspath(path), state_dict, force=True)
        if not self.use_async:
            self._ckptr.wait_until_finished()
        # async_save: orbax's background thread drains the disk write while the
        # caller proceeds to the side-state writes/barrier; engine.save_checkpoint's
        # closing commit() is the durability barrier, so the overlap is WITHIN
        # save_checkpoint (engine semantics require a durable checkpoint before
        # 'latest' advances — full resume-while-draining would defer commit to the
        # next save)

    def load(self, path: str, map_location=None, template: Any = None,
             shardings: Any = None) -> Any:
        if path.endswith(".json"):
            with open(path) as f:
                return json.load(f)
        if path.endswith(".pkl"):
            with open(path, "rb") as f:
                return pickle.load(f)
        import jax
        if template is not None:
            abstract = jax.tree_util.tree_map(
                lambda l, s=None: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                                       sharding=s) if hasattr(l, "shape") else l,
                template)
            if shardings is not None:
                abstract = jax.tree_util.tree_map(
                    lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s)
                    if hasattr(l, "shape") else l,
                    template, shardings)
            return self._ckptr.restore(os.path.abspath(path), abstract)
        return self._ckptr.restore(os.path.abspath(path))

    def load_subtree(self, path: str, key: str, template: Any, shardings: Any = None):
        """Restore one top-level entry (e.g. just ``params``) from a full training
        checkpoint without materialising the rest (optimizer state etc.) — the inference
        engine's sharded-load path."""
        import jax
        ocp = self._ocp
        if shardings is not None:
            abstract = jax.tree_util.tree_map(
                lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s)
                if hasattr(l, "shape") else l, template, shardings)
        else:
            abstract = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype)
                if hasattr(l, "shape") else l, template)
        with ocp.PyTreeCheckpointer() as ckptr:
            restored = ckptr.restore(
                os.path.abspath(path),
                args=self._ocp.args.PyTreeRestore(item={key: abstract},
                                                  partial_restore=True))
        return restored[key]

    def commit(self, tag: str) -> bool:
        self._ckptr.wait_until_finished()
        return super().commit(tag)


def make_checkpoint_engine(checkpoint_config=None) -> CheckpointEngine:
    use_async = bool(getattr(checkpoint_config, "async_save", False))
    return OrbaxCheckpointEngine(checkpoint_config, use_async=use_async)
