"""Random-LTD (reference data_pipeline/data_routing)."""
from .scheduler import RandomLTDScheduler
from .basic_layer import random_ltd_layer, token_drop, token_restore
