"""Random layerwise token dropping (random-LTD) scheduler.

Behavioural equivalent of reference
``deepspeed/runtime/data_pipeline/data_routing/scheduler.py`` (``BaseScheduler:15``,
``RandomLTDScheduler:39``): schedules the per-layer *kept* sequence length from
``min_value`` up to ``max_value`` (the full length) over ``total_layer_saving_step``
steps, and accounts consumed layer-tokens. The actual token selection on TPU is a
jit-safe gather by a per-step random permutation prefix (see ``basic_layer.py``).
"""

import math
from typing import Dict


class BaseScheduler:

    def __init__(self):
        self.state: Dict = {}

    def _fixed_root_get_value(self, global_steps: int, root_degree=None) -> int:
        sc = self.state["schedule_config"]
        if root_degree is None:
            root_degree = sc["root_degree"]
        progress = (float(global_steps) / sc["total_layer_saving_step"]) \
            ** (1.0 / root_degree)
        next_seq = math.floor(
            progress * (self.state["max_value"] - self.state["min_value"])
            + self.state["min_value"])
        next_seq -= next_seq % sc["seq_per_step"]
        return min(next_seq, self.state["max_value"])

    def get_value(self, global_steps: int) -> int:
        if self.state["schedule_type"] == "fixed_linear":
            return self._fixed_root_get_value(global_steps, 1)
        raise RuntimeError(
            f"Unsupported random-LTD schedule type {self.state['schedule_type']!r}")


class RandomLTDScheduler(BaseScheduler):
    """Config keys match the reference ("random_ltd" block)::

        {"enabled": true, "total_layer_num": 24, "random_ltd_layer_num": 22,
         "model_mask_name": ..., "model_type": "decoder",
         "hidden_state_order": "batch_seq_dim",
         "random_ltd_schedule": {"min_value": 128, "max_value": 2048,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_layer_saving_step": 10000, "seq_per_step": 16}}}
    """

    def __init__(self, config: Dict):
        super().__init__()
        self.model_layer_num = config["total_layer_num"]
        self.random_ltd_layer_num = config["random_ltd_layer_num"]
        self.config_schedule = config.get("random_ltd_schedule")
        self.global_batch_size = config.get("global_batch_size")
        self.reset_to_init()

    def reset_to_init(self):
        if self.config_schedule is not None:
            self.state["min_value"] = self.config_schedule["min_value"]
            self.state["max_value"] = self.config_schedule["max_value"]
            self.state["current_value"] = self.config_schedule["min_value"]
            self.state["schedule_type"] = self.config_schedule["schedule_type"]
            self.state["schedule_config"] = self.config_schedule["schedule_config"]
        self.state["consumed_layer_tokens"] = 0
        self.state["curr_step"] = -1

    # ------------------------------------------------------------------ queries
    def get_current_seq(self) -> int:
        return self.state["current_value"]

    def set_current_seq(self, seq_length: int):
        self.state["current_value"] = seq_length

    def get_random_ltd_layer_num(self) -> int:
        return self.random_ltd_layer_num

    def get_state(self) -> Dict:
        return self.state

    def set_state(self, state: Dict):
        self.state = state

    def update_seq(self, global_steps: int) -> int:
        """Advance the schedule one step; accounts layer-tokens consumed
        (reference ``update_seq:88``)."""
        if self.state["current_value"] < self.state["max_value"]:
            self.state["current_value"] = self.get_value(global_steps)
        if global_steps != self.state["curr_step"]:
            if self.global_batch_size is not None:
                kept = self.state["current_value"]
                full = self.state["max_value"]
                self.state["consumed_layer_tokens"] += self.global_batch_size * (
                    kept * self.random_ltd_layer_num +
                    full * (self.model_layer_num - self.random_ltd_layer_num))
            self.state["curr_step"] = global_steps
        return self.state["current_value"]

    def get_total_layer_tokens(self, train_iters: int) -> int:
        """Total layer-tokens over a full run (reference :55)."""
        for step in range(train_iters):
            self.update_seq(step)
        return self.state["consumed_layer_tokens"]
