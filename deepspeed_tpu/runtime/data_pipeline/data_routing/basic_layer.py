"""Random-LTD token drop/restore ops.

Behavioural equivalent of reference
``deepspeed/runtime/data_pipeline/data_routing/basic_layer.py``
(``RandomLayerTokenDrop``): drop a random subset of tokens before a transformer layer
and scatter the layer's outputs back into the full sequence, so the layer trains on a
shorter (cheaper) sequence while the residual stream keeps full length.

TPU-native shape discipline: ``kept_len`` is a static Python int (the scheduler changes
it only every ``seq_per_step`` steps, so recompiles are rare and cached); the selection
is a prefix of ``jax.random.permutation``, gathered with ``jnp.take`` and restored with a
scatter — all static-shape, jit-safe.
"""

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def token_drop(x: jnp.ndarray, rng, kept_len: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Select ``kept_len`` random token positions (per batch row shared selection,
    matching the reference's single mask per step). ``x``: (B, T, ...) → ((B, kept, ...),
    sorted indices (kept,))."""
    t = x.shape[1]
    if not (0 < kept_len <= t):
        raise AssertionError((kept_len, t))
    idx = jnp.sort(jax.random.permutation(rng, t)[:kept_len])
    return jnp.take(x, idx, axis=1), idx


def token_restore(full_x: jnp.ndarray, updated: jnp.ndarray,
                  idx: jnp.ndarray) -> jnp.ndarray:
    """Scatter layer outputs for kept tokens back into the full-length stream; dropped
    tokens keep their pre-layer values (the residual-passthrough of the reference)."""
    return full_x.at[:, idx].set(updated)


def random_ltd_layer(layer_fn: Callable, x: jnp.ndarray, rng, kept_len: int,
                     *layer_args, **layer_kwargs) -> jnp.ndarray:
    """Wrap one layer application with drop→apply→restore (reference
    ``RandomLayerTokenDrop.forward``)."""
    if kept_len >= x.shape[1]:
        return layer_fn(x, *layer_args, **layer_kwargs)
    short, idx = token_drop(x, rng, kept_len)
    out = layer_fn(short, *layer_args, **layer_kwargs)
    return token_restore(x, out, idx)
