"""Data efficiency suite (reference ``deepspeed/runtime/data_pipeline``): curriculum
learning, random-LTD token dropping, indexed datasets."""
from .curriculum_scheduler import CurriculumScheduler
from .data_routing.scheduler import RandomLTDScheduler
