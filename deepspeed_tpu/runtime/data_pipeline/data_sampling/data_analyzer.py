"""Offline difficulty-metric analysis — the producer of curriculum metric files.

Behavioural equivalent of reference
``deepspeed/runtime/data_pipeline/data_sampling/data_analyzer.py`` (``DataAnalyzer:18``):
a map/reduce over the training corpus that computes per-sample difficulty metrics
(sequence length, vocabulary rarity, ...) ahead of training, so the curriculum sampler
(:class:`~.data_sampler.DeepSpeedDataSampler`) can gate eligibility without touching the
model. Re-designed for the single-controller stack:

- **map**: each worker computes its contiguous shard of the dataset and writes one
  ``worker{i}.npz`` per metric (the reference writes per-thread mmap builders; plain
  ``.npz`` shards hold the same content with numpy-native IO — the merge is
  concatenation either way).
- **reduce**: any process merges the worker files into the final artifacts:
  ``{metric}/sample_to_metric.npy`` (per-sample values, the array the sampler
  consumes), ``{metric}/metric_to_sample.npz`` (value → sample-id clusters, the
  reference's reverse index), and ``{metric}/metric_value.npy`` for
  ``accumulate_value_over_samples`` metrics.

Metric functions take the COLLATED batch (whatever ``dataset[i]`` or ``collate_fn``
yields) and return one value per sample — the reference's contract.
"""

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ....utils.logging import logger

METRIC_SINGLE = "single_value_per_sample"
METRIC_ACCUMULATE = "accumulate_value_over_samples"


class DataAnalyzer:
    """Map/reduce difficulty metrics over a dataset.

    ``num_workers``/``worker_id``: this process computes samples
    ``[worker_id * n / num_workers, (worker_id + 1) * n / num_workers)``; each worker
    calls :meth:`run_map`, then one process calls :meth:`run_reduce` once all worker
    files exist (the reference uses the same split + merge contract).
    """

    def __init__(self, dataset: Sequence, metric_names: List[str],
                 metric_functions: List[Callable], metric_types: List[str],
                 num_workers: int = 1, worker_id: int = 0, batch_size: int = 64,
                 save_path: str = "./data_analysis",
                 collate_fn: Optional[Callable] = None,
                 metric_dtypes: Optional[List[Any]] = None):
        if not (len(metric_names) == len(metric_functions) == len(metric_types)):
            raise AssertionError('len(metric_names) == len(metric_functions) == len(metric_types)')
        if not (0 <= worker_id < num_workers):
            raise AssertionError('0 <= worker_id < num_workers')
        for t in metric_types:
            if not (t in (METRIC_SINGLE, METRIC_ACCUMULATE)):
                raise AssertionError(t)
        self.dataset = dataset
        self.metric_names = metric_names
        self.metric_functions = metric_functions
        self.metric_types = metric_types
        self.metric_dtypes = metric_dtypes or [np.int64] * len(metric_names)
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size
        self.save_path = save_path
        self.collate_fn = collate_fn

    # ------------------------------------------------------------------ map
    def _shard_range(self):
        n = len(self.dataset)
        lo = self.worker_id * n // self.num_workers
        hi = (self.worker_id + 1) * n // self.num_workers
        return lo, hi

    def _worker_file(self, metric: str, worker_id: int) -> str:
        return os.path.join(self.save_path, metric, f"worker{worker_id}.npz")

    def run_map(self):
        """Compute this worker's shard; write one npz per metric."""
        lo, hi = self._shard_range()
        per_metric: List[List[np.ndarray]] = [[] for _ in self.metric_names]
        for start in range(lo, hi, self.batch_size):
            idxs = list(range(start, min(start + self.batch_size, hi)))
            rows = [self.dataset[i] for i in idxs]
            batch = self.collate_fn(rows) if self.collate_fn is not None else rows
            for mi, fn in enumerate(self.metric_functions):
                vals = np.asarray(fn(batch))
                if self.metric_types[mi] == METRIC_SINGLE:
                    if not (vals.shape[0] == len(idxs)):
                        raise AssertionError(f"metric {self.metric_names[mi]!r} returned "
                         f"{vals.shape[0]} values for {len(idxs)} samples")
                per_metric[mi].append(vals)
        for mi, name in enumerate(self.metric_names):
            os.makedirs(os.path.join(self.save_path, name), exist_ok=True)
            if self.metric_types[mi] == METRIC_SINGLE:
                arr = (np.concatenate(per_metric[mi])
                       if per_metric[mi] else np.zeros(0, self.metric_dtypes[mi]))
                arr = arr.astype(self.metric_dtypes[mi])
            else:
                arr = np.sum([np.asarray(v) for v in per_metric[mi]], axis=0) \
                    if per_metric[mi] else np.zeros((), self.metric_dtypes[mi])
            np.savez(self._worker_file(name, self.worker_id),
                     values=arr, lo=lo, hi=hi)
        logger.info(f"DataAnalyzer map: worker {self.worker_id}/{self.num_workers} "
                    f"wrote samples [{lo}, {hi}) for {len(self.metric_names)} metrics")

    # ------------------------------------------------------------------ reduce
    def run_reduce(self):
        """Merge all workers' files into the final per-metric artifacts."""
        n = len(self.dataset)
        for mi, name in enumerate(self.metric_names):
            shards = []
            for w in range(self.num_workers):
                f = self._worker_file(name, w)
                if not (os.path.isfile(f)):
                    raise AssertionError(f"missing {f} — did worker {w} finish run_map()?")
                shards.append(np.load(f))
            mdir = os.path.join(self.save_path, name)
            # the shards must stitch to exactly [0, n): a num_workers mismatch
            # between map and reduce would otherwise ship silent zeros
            ranges = sorted((int(s["lo"]), int(s["hi"])) for s in shards)
            covered = ranges[0][0] == 0 and ranges[-1][1] == n and all(
                a[1] == b[0] for a, b in zip(ranges, ranges[1:]))
            if not (covered):
                raise AssertionError(f"worker shards {ranges} do not cover [0, {n}) — was run_map "
                 f"executed with a different num_workers than this reduce?")
            if self.metric_types[mi] == METRIC_SINGLE:
                full = np.zeros(n, self.metric_dtypes[mi])
                for s in shards:
                    full[int(s["lo"]):int(s["hi"])] = s["values"]
                np.save(os.path.join(mdir, "sample_to_metric.npy"), full)
                # reverse index (reference metric_to_sample): value → sample ids,
                # stored as one sorted permutation + cluster boundaries
                order = np.argsort(full, kind="stable")
                uniq, starts = np.unique(full[order], return_index=True)
                np.savez(os.path.join(mdir, "metric_to_sample.npz"),
                         values=uniq, starts=starts, sample_order=order)
            else:
                total = np.sum([s["values"] for s in shards], axis=0)
                np.save(os.path.join(mdir, "metric_value.npy"), total)
        with open(os.path.join(self.save_path, "analysis_meta.json"), "w") as f:
            json.dump({"num_samples": n, "metrics": self.metric_names,
                       "types": self.metric_types,
                       "num_workers": self.num_workers}, f)
        logger.info(f"DataAnalyzer reduce: merged {self.num_workers} workers over "
                    f"{n} samples → {self.save_path}")


def load_metric_values(save_path: str,
                       metric_names: Optional[List[str]] = None
                       ) -> Dict[str, np.ndarray]:
    """Load reduced ``sample_to_metric`` arrays — the ``metric_values`` dict the
    curriculum :class:`~.data_sampler.DeepSpeedDataSampler` consumes."""
    if metric_names is None:
        with open(os.path.join(save_path, "analysis_meta.json")) as f:
            metric_names = json.load(f)["metrics"]
    out = {}
    for name in metric_names:
        f = os.path.join(save_path, name, "sample_to_metric.npy")
        if os.path.isfile(f):
            out[name] = np.load(f)
    return out


# ------------------------------------------------------------------ stock metrics
def _token_rows(batch):
    """Normalise the accepted batch forms to a list of token arrays: a collated dict
    of stacked ids, a list of per-sample dicts, or a list of raw arrays."""
    if isinstance(batch, dict):
        return list(np.asarray(batch["input_ids"]))
    return [np.asarray(r["input_ids"] if isinstance(r, dict) else r)
            for r in batch]


def metric_seqlen(pad_token_id: int = 0) -> Callable:
    """Per-sample non-pad token count — the reference's canonical curriculum metric
    (``seqlen`` in the data-efficiency examples)."""
    def fn(batch):
        return np.asarray([int(np.sum(r != pad_token_id))
                           for r in _token_rows(batch)], np.int64)
    return fn


def metric_vocab_rarity(vocab_size: int, token_counts: np.ndarray,
                        pad_token_id: Optional[int] = 0) -> Callable:
    """Mean negative-log-frequency of a sample's NON-PAD tokens (reference
    ``vocabularyrarity``): higher = rarer vocabulary = harder sample. Padding is
    excluded (it is the most frequent token by construction and would score heavily
    padded samples 'easy' regardless of content); pass ``pad_token_id=None`` for
    unpadded corpora. Values are scaled ×1e6 to integers, as the reference requires
    integer metrics."""
    freq = token_counts.astype(np.float64) / max(1.0, float(token_counts.sum()))
    logf = -np.log(np.clip(freq, 1e-12, None))

    def fn(batch):
        out = []
        for r in _token_rows(batch):
            if pad_token_id is not None:
                r = r[r != pad_token_id]
            out.append(int(1e6 * float(np.mean(logf[r]))) if r.size else 0)
        return np.asarray(out, np.int64)
    return fn
