"""Curriculum-learning data sampler.

Behavioural equivalent of reference
``deepspeed/runtime/data_pipeline/data_sampling/data_sampler.py``
(``DeepSpeedDataSampler:33``): compose global batches from the subset of samples whose
difficulty metrics fall inside the curriculum's current bound, advancing the bound with
:class:`CurriculumScheduler` every global batch.

Single-controller simplifications (documented, not silent): the reference stores
per-difficulty clusters as mmap datasets on rank 0 and broadcasts batches over the DP
group; here eligibility is computed from in-memory (or :class:`MMapIndexedDataset`-
backed) metric arrays and every rank derives the same batch from the shared rng —
equivalent semantics without the broadcast. Supported per-metric knobs match the
reference: ``difficulty_type`` value/percentile, schedules via the shared curriculum
scheduler; ``clustering_type: single_cluster`` means the metric does not gate
eligibility (reference semantics).
"""

from typing import Dict, Iterator, List, Optional

import numpy as np

from ..curriculum_scheduler import CurriculumScheduler

CURRICULUM_LEARNING_VALUE_BASED = "value"
CURRICULUM_LEARNING_PERCENTILE_BASED = "percentile"
CURRICULUM_LEARNING_SINGLE_CLUSTER = "single_cluster"


class DeepSpeedDataSampler:
    """Yields per-rank microbatch index arrays, curriculum-gated.

    ``metric_values``: dict metric name → (n_samples,) array of difficulty values
    (e.g. sequence length, loss-based score). Metrics configured with
    ``clustering_type: single_cluster`` need no values.
    """

    def __init__(self, data_efficiency_config: Dict, one_epoch_total_samples: int,
                 micro_batch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, gradient_accumulation_steps: int,
                 metric_values: Optional[Dict[str, np.ndarray]] = None,
                 drop_last: bool = True):
        ds_cfg = data_efficiency_config.get("data_sampling", {})
        self.num_epochs = ds_cfg.get("num_epochs", 1)
        self.one_epoch_total_samples = int(one_epoch_total_samples)
        self.total_samples = self.one_epoch_total_samples * self.num_epochs
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.gradient_accumulation_steps = gradient_accumulation_steps
        self.global_batch_size = (micro_batch_size * data_parallel_size *
                                  gradient_accumulation_steps)
        self.drop_last = drop_last
        self.np_rng = np.random.default_rng(
            data_efficiency_config.get("seed", 1234))
        if not (self.total_samples > 0 and micro_batch_size > 0):
            raise AssertionError('self.total_samples > 0 and micro_batch_size > 0')
        if not (data_parallel_rank < data_parallel_size):
            raise AssertionError('data_parallel_rank < data_parallel_size')

        self.consumed_samples = 0
        self.curriculum_step = 0
        self.curriculum_schedulers: Dict[str, CurriculumScheduler] = {}
        self.difficulty_type: Dict[str, str] = {}
        self.clustering_type: Dict[str, str] = {}
        self.current_difficulties: Dict[str, int] = {}
        self._metric_values: Dict[str, np.ndarray] = {}
        self._metric_order: Dict[str, np.ndarray] = {}

        cl = ds_cfg.get("curriculum_learning", {})
        self.curriculum_enabled = cl.get("enabled", False)
        if self.curriculum_enabled:
            for metric, mcfg in cl.get("curriculum_metrics", {}).items():
                self.curriculum_schedulers[metric] = CurriculumScheduler(mcfg)
                self.difficulty_type[metric] = mcfg.get(
                    "difficulty_type", CURRICULUM_LEARNING_VALUE_BASED)
                self.clustering_type[metric] = mcfg.get(
                    "clustering_type", "schedule_based")
                self.current_difficulties[metric] = \
                    self.curriculum_schedulers[metric].get_current_difficulty()
                if self.clustering_type[metric] != CURRICULUM_LEARNING_SINGLE_CLUSTER:
                    if not (metric_values is not None and metric in metric_values):
                        raise AssertionError(f"curriculum metric {metric!r} needs metric_values")
                    vals = np.asarray(metric_values[metric])
                    if not (vals.shape[0] == self.one_epoch_total_samples):
                        raise AssertionError('vals.shape[0] == self.one_epoch_total_samples')
                    self._metric_values[metric] = vals
                    self._metric_order[metric] = np.argsort(vals, kind="stable")
        self._pool: List[int] = []
        self._warned_empty = False

    def __len__(self) -> int:
        return self.total_samples

    def set_custom_curriculum_learning_schedule(self, schedule_func_dict: Dict):
        """Reference :122 — plug custom difficulty schedules per metric."""
        for metric, fn in schedule_func_dict.items():
            if metric in self.curriculum_schedulers:
                self.curriculum_schedulers[metric].set_custom_get_difficulty(fn)

    # ------------------------------------------------------------------ eligibility
    def _eligible(self) -> np.ndarray:
        """Sample indices whose every gated metric is within its current bound
        (value: metric <= difficulty; percentile: lowest d% by metric —
        reference get_sample_based_on_metric_{value,percentile})."""
        mask = np.ones(self.one_epoch_total_samples, dtype=bool)
        for metric, vals in self._metric_values.items():
            d = self.current_difficulties[metric]
            if self.difficulty_type[metric] == CURRICULUM_LEARNING_VALUE_BASED:
                mask &= vals <= d
            else:
                # difficulty IS a percentile (reference scale: d of 100); a
                # max_difficulty below 100 permanently excludes the hardest tail
                max_d = self.curriculum_schedulers[metric].state["max_difficulty"]
                k = int(self.one_epoch_total_samples * min(d, max_d) / 100.0)
                sel = np.zeros_like(mask)
                sel[self._metric_order[metric][:max(k, 1)]] = True
                mask &= sel
        idx = np.nonzero(mask)[0]
        if not idx.size:
            if not self._warned_empty:
                self._warned_empty = True
                from ....utils.logging import logger
                logger.warning(
                    "curriculum: NO sample satisfies the current difficulty bounds "
                    f"({self.current_difficulties}) — falling back to the full "
                    "dataset; check min_difficulty against the metric range")
            return np.arange(self.one_epoch_total_samples)
        return idx

    def _refill_pool(self, exclude=()):
        eligible = self._eligible()
        if exclude:
            filtered = eligible[~np.isin(eligible, list(exclude))]
            # only when the eligible set is smaller than one global batch do we
            # allow repeats within a batch (unavoidable)
            eligible = filtered if filtered.size else eligible
        self._pool = list(self.np_rng.permutation(eligible))

    def get_next_global_batch(self) -> np.ndarray:
        """Reference :299 — advance difficulties, then draw the next global batch
        from the eligible pool (reshuffling on exhaustion; a mid-batch reshuffle
        excludes the batch's own samples so one batch never double-counts)."""
        if self.curriculum_enabled:
            self.curriculum_step += 1
            changed = False
            for metric, sched in self.curriculum_schedulers.items():
                new_d = sched.update_difficulty(self.curriculum_step)
                if new_d != self.current_difficulties[metric]:
                    changed = True
                self.current_difficulties[metric] = new_d
            if changed:
                self._pool = []  # difficulty moved: re-derive eligibility
        batch = []
        while len(batch) < self.global_batch_size:
            if not self._pool:
                self._refill_pool(exclude=set(batch))
            batch.append(self._pool.pop())
        return np.asarray(batch, dtype=np.int64)

    # ------------------------------------------------------------------ iteration
    def get_start_end_idx(self):
        start = self.data_parallel_rank * self.micro_batch_size
        return start, start + self.micro_batch_size

    def __iter__(self) -> Iterator[np.ndarray]:
        start, end = self.get_start_end_idx()
        while self.consumed_samples < self.total_samples:
            remaining = self.total_samples - self.consumed_samples
            if remaining < self.global_batch_size and self.drop_last:
                return
            gb = self.get_next_global_batch()
            if remaining < self.global_batch_size:
                # drop_last=False: pad the final batch by wrapping its own leading
                # samples (Megatron-style) so every rank/microbatch keeps its full
                # static shape; only the true remainder counts as consumed
                pad = np.resize(gb[:remaining], self.global_batch_size)
                gb = pad
                self.consumed_samples += remaining
            else:
                self.consumed_samples += len(gb)
            per_round = self.data_parallel_size * self.micro_batch_size
            for i in range(0, len(gb), per_round):
                yield gb[i:i + per_round][start:end]

    # ------------------------------------------------------------------ state
    def state_dict(self) -> Dict:
        return {
            "consumed_samples": self.consumed_samples,
            "curriculum_step": self.curriculum_step,
            "current_difficulties": dict(self.current_difficulties),
            "np_rng_state": self.np_rng.bit_generator.state,
            # the partially-consumed pool: without it a resume would reshuffle and
            # could repeat samples the interrupted epoch already served
            "pool": list(self._pool),
        }

    def load_state_dict(self, sd: Dict):
        self.consumed_samples = sd["consumed_samples"]
        self.curriculum_step = sd["curriculum_step"]
        self.current_difficulties = dict(sd["current_difficulties"])
        self.np_rng.bit_generator.state = sd["np_rng_state"]
        for metric, d in self.current_difficulties.items():
            if metric in self.curriculum_schedulers:
                self.curriculum_schedulers[metric].set_current_difficulty(d)
        self._pool = [int(i) for i in sd.get("pool", [])]
