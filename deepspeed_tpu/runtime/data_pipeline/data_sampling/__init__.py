"""Data sampling (reference data_pipeline/data_sampling)."""
from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder, make_dataset)
from .data_sampler import DeepSpeedDataSampler
