"""Memory-mapped indexed dataset (Megatron/fairseq ``.bin``/``.idx`` format).

Behavioural equivalent of reference
``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py`` (``MMapIndexedDataset``,
``MMapIndexedDatasetBuilder``, 645 LoC): token sequences stored back-to-back in a flat
binary ``.bin``, with an ``.idx`` sidecar of per-document sizes and byte pointers. This
implementation reads and writes the same on-disk format (magic ``MMIDIDX``, version 1,
dtype code table) so corpora tokenised for Megatron/DeepSpeed load unchanged; the reader
is a numpy memmap — zero-copy slices feed the host input pipeline.
"""

import os
import struct
from typing import List, Optional

import numpy as np

_INDEX_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

# reference dtype code table (indexed_dataset.py `dtypes`)
DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
          6: np.float32, 7: np.float64, 8: np.uint16}
DTYPE_CODES = {np.dtype(v): k for k, v in DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDataset:
    """Read-only memory-mapped view: ``ds[i]`` → numpy array of document ``i``."""

    def __init__(self, path_prefix: str):
        self._path = path_prefix
        with open(index_file_path(path_prefix), "rb") as f:
            magic = f.read(9)
            if magic != _INDEX_MAGIC:
                raise ValueError(f"{index_file_path(path_prefix)}: bad magic {magic!r} "
                                 "(not an MMIDIDX index)")
            version, = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            dtype_code, = struct.unpack("<B", f.read(1))
            self._dtype = np.dtype(DTYPES[dtype_code])
            n_seqs, = struct.unpack("<Q", f.read(8))
            n_docs, = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx_buf = np.memmap(index_file_path(path_prefix), mode="r", order="C")
        self._sizes = np.frombuffer(idx_buf, dtype=np.int32, count=n_seqs,
                                    offset=offset)
        self._pointers = np.frombuffer(idx_buf, dtype=np.int64, count=n_seqs,
                                       offset=offset + self._sizes.nbytes)
        self._doc_idx = np.frombuffer(
            idx_buf, dtype=np.int64, count=n_docs,
            offset=offset + self._sizes.nbytes + self._pointers.nbytes)
        self._data = np.memmap(data_file_path(path_prefix), mode="r", order="C")

    def __len__(self) -> int:
        return len(self._sizes)

    def __getitem__(self, i: int) -> np.ndarray:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        size = int(self._sizes[i])
        ptr = int(self._pointers[i])
        return np.frombuffer(self._data, dtype=self._dtype, count=size, offset=ptr)

    def get(self, i: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        """Sub-slice of document ``i`` without materialising the whole doc."""
        size = int(self._sizes[i])
        length = size - offset if length is None else length
        ptr = int(self._pointers[i]) + offset * self._dtype.itemsize
        return np.frombuffer(self._data, dtype=self._dtype, count=length, offset=ptr)

    @property
    def sizes(self) -> np.ndarray:
        return self._sizes

    @property
    def doc_idx(self) -> np.ndarray:
        return self._doc_idx

    @property
    def dtype(self):
        return self._dtype

    @staticmethod
    def exists(path_prefix: str) -> bool:
        return (os.path.exists(index_file_path(path_prefix)) and
                os.path.exists(data_file_path(path_prefix)))


class MMapIndexedDatasetBuilder:
    """Streaming writer producing the same format (reference
    ``MMapIndexedDatasetBuilder``)."""

    def __init__(self, out_prefix: str, dtype=np.int32):
        self._prefix = out_prefix
        self._dtype = np.dtype(dtype)
        self._data_file = open(data_file_path(out_prefix), "wb")
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._data_file.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def finalize(self) -> None:
        self._data_file.close()
        itemsize = self._dtype.itemsize
        sizes_bytes = np.asarray(self._sizes, dtype=np.int64) * itemsize
        pointers = np.zeros(len(self._sizes), dtype=np.int64)
        if len(self._sizes) > 1:
            pointers[1:] = np.cumsum(sizes_bytes[:-1])
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_INDEX_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", DTYPE_CODES[self._dtype]))
            f.write(struct.pack("<Q", len(self._sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            f.write(np.asarray(self._sizes, dtype=np.int32).tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, dtype=np.int64).tobytes(order="C"))


def make_dataset(path_prefix: str, impl: str = "mmap") -> MMapIndexedDataset:
    """Reference ``make_dataset``: only the mmap impl exists on TPU (cached/lazy impls
    were CPU-side anyway and mmap supersedes them)."""
    if impl not in ("mmap", "infer"):
        raise ValueError(f"indexed dataset impl {impl!r} not supported (use 'mmap')")
    return MMapIndexedDataset(path_prefix)
