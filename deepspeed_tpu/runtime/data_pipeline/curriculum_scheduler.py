"""Curriculum learning scheduler.

Behavioural equivalent of reference ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py``
(``CurriculumScheduler:10``): maps global step → difficulty (e.g. sequence length) under
``fixed_linear`` / ``fixed_root`` / ``fixed_discrete`` / ``custom`` schedules. Pure host
logic; the difficulty value is consumed by the data pipeline (truncate/re-bucket batches)
so nothing here touches the compiled step.

Config keys match the reference ("curriculum_learning" block)::

    {"enabled": true, "curriculum_type": "seqlen",
     "min_difficulty": 8, "max_difficulty": 1024,
     "schedule_type": "fixed_linear",
     "schedule_config": {"total_curriculum_step": 15000, "difficulty_step": 8}}
"""

import math
from typing import Callable, Dict, Optional


class CurriculumScheduler:

    def __init__(self, config: Dict):
        for key in ("min_difficulty", "max_difficulty", "schedule_type"):
            if not (key in config):
                raise AssertionError(f"Curriculum learning requires the config '{key}'")
        self.state = {
            "min_difficulty": config["min_difficulty"],
            "max_difficulty": config["max_difficulty"],
            "current_difficulty": config["min_difficulty"],
            "schedule_type": config["schedule_type"],
        }
        self.first_step = True
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None
        stype = config["schedule_type"]
        sconfig = config.get("schedule_config", {})
        if stype == "fixed_discrete":
            # difficulty has one more entry than max_step: the last difficulty holds
            # for all remaining steps (reference :29-56)
            if not ("difficulty" in sconfig and "max_step" in sconfig):
                raise AssertionError('"difficulty" in sconfig and "max_step" in sconfig')
            if not (len(sconfig["difficulty"]) == len(sconfig["max_step"]) + 1):
                raise AssertionError('len(sconfig["difficulty"]) == len(sconfig["max_step"]) + 1')
            if not (len(sconfig["max_step"]) > 0):
                raise AssertionError('len(sconfig["max_step"]) > 0')
        elif stype in ("fixed_linear", "fixed_root"):
            if not ("total_curriculum_step" in sconfig):
                raise AssertionError('"total_curriculum_step" in sconfig')
            if not ("difficulty_step" in sconfig):
                raise AssertionError('"difficulty_step" in sconfig')
            if stype == "fixed_root":
                if not ("root_degree" in sconfig):
                    raise AssertionError('"root_degree" in sconfig')
            if sconfig["difficulty_step"] % 8 != 0:
                # TPU note kept from the reference warning: sequence lengths that are
                # not multiples of 8 hurt matmul tiling (here: MXU lanes)
                import warnings
                warnings.warn("difficulty_step not a multiple of 8 may reduce matmul "
                              "efficiency (tile-aligned lengths recommended)")
        elif stype == "custom":
            pass
        else:
            raise RuntimeError(f"Unsupported curriculum schedule type {stype!r}")
        self.state["schedule_config"] = sconfig

    # ------------------------------------------------------------------ queries
    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty: int):
        self.state["current_difficulty"] = difficulty

    def set_custom_get_difficulty(self, fn: Callable[[int], int]):
        self.custom_get_difficulty = fn

    def get_state(self) -> Dict:
        return self.state

    def set_state(self, state: Dict):
        self.state = state

    def _fixed_discrete(self, global_steps: int) -> int:
        sc = self.state["schedule_config"]
        if global_steps > sc["max_step"][-1]:
            return sc["difficulty"][-1]
        for i, boundary in enumerate(sc["max_step"]):
            if global_steps <= boundary:
                return sc["difficulty"][i]
        return sc["difficulty"][-1]

    def _fixed_root(self, global_steps: int, root_degree: Optional[int] = None) -> int:
        sc = self.state["schedule_config"]
        if root_degree is None:
            root_degree = sc["root_degree"]
        progress = (float(global_steps) / sc["total_curriculum_step"]) \
            ** (1.0 / root_degree)
        next_difficulty = math.floor(
            progress * (self.state["max_difficulty"] - self.state["min_difficulty"])
            + self.state["min_difficulty"])
        next_difficulty -= next_difficulty % sc["difficulty_step"]
        return min(next_difficulty, self.state["max_difficulty"])

    def get_difficulty(self, global_steps: int) -> int:
        stype = self.state["schedule_type"]
        if stype == "fixed_discrete":
            return self._fixed_discrete(global_steps)
        if stype == "fixed_linear":
            return self._fixed_root(global_steps, 1)
        if stype == "fixed_root":
            return self._fixed_root(global_steps)
        if stype == "custom":
            if not (self.custom_get_difficulty is not None):
                raise AssertionError("custom schedule requires set_custom_get_difficulty()")
            return self.custom_get_difficulty(global_steps)
        raise RuntimeError(f"Unsupported curriculum schedule type {stype!r}")

    def update_difficulty(self, global_steps: int) -> int:
        if self.state["current_difficulty"] < self.state["max_difficulty"]:
            self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]
