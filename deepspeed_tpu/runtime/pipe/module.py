"""Pipeline module: LayerSpec / TiedLayerSpec / PipelineModule.

Reference: ``deepspeed/runtime/pipe/module.py`` (``LayerSpec:26``, ``TiedLayerSpec:74``,
``PipelineModule:88``, partitioning ``_partition_layers:367``, tied weights ``:423-445``).

TPU-native redesign: instead of materialising per-stage ``nn.Sequential`` fragments in separate
processes, the module classifies its layer list into

- ``pre``  — leading heterogeneous layers (embeddings…), computed on every device (replicated
  over the ``pipe`` axis, sharded over data/tensor axes as usual);
- ``body`` — the longest homogeneous run of layers (the transformer blocks): their params are
  *stacked* along a leading layer dimension and sharded over the ``pipe`` mesh axis, so each
  stage physically holds only its own blocks;
- ``post`` — trailing layers (final norm, LM head), replicated like ``pre``.

The pipelined forward is an SPMD collective-permute loop (GPipe fill-drain over
``micro_batches + stages - 1`` iterations) under ``jax.shard_map`` manual only over ``pipe``;
``jax.lax.ppermute`` moves activations stage→stage+1 and autodiff through the loop transposes it
into the backward drain (reverse permutes), giving the 1F1B-equivalent bubble fraction
``(S-1)/(M+S-1)``. Activation memory is bounded by per-microbatch remat (``jax.checkpoint``) —
the role 1F1B plays in the reference.

Tied layers (``TiedLayerSpec``) share one parameter entry under ``params['tied'][key]``; since
pre/post are replicated over ``pipe`` there is no tied-weight gradient all-reduce to schedule —
XLA's psum over the batch axes already covers it.
"""

import re
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...parallel.mesh import AXIS_EXPERT, AXIS_PIPE, MeshSpec
from ...utils.logging import logger
from ...utils.jax_compat import shard_map


# --------------------------------------------------------------------------- layer contract
class PipeLayer:
    """A pipeline layer: ``init(rng, x) -> params`` and ``apply(params, x, rng) -> y``.

    Layers with an auxiliary scalar loss (MoE load-balancing) set ``has_aux = True``
    and implement ``apply_with_aux(params, x, rng) -> (y, aux)``; the 1F1B executor
    aggregates aux across layers, stages and microbatches into the total loss
    (reference MoE aux-loss plumbing through the pipeline engine)."""

    has_aux = False

    def init(self, rng, x):
        return {}

    def apply(self, params, x, rng=None):
        raise NotImplementedError

    def apply_with_aux(self, params, x, rng=None):
        return self.apply(params, x, rng), jnp.float32(0.0)


class LambdaLayer(PipeLayer):
    """Parameterless function layer (reference allows bare callables in the layer list)."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def apply(self, params, x, rng=None):
        return self.fn(x)


class FlaxPipeLayer(PipeLayer):
    """Adapt a ``flax.linen`` module to the PipeLayer contract.

    ``deterministic_kwarg``: pass ``deterministic=(rng is None)`` through to the module (the
    convention of our transformer blocks).

    Tensor-parallel support (body layers only): ``tp_apply_factory(tp, axis)`` returns a
    manual-collective forward consuming LOCAL parameter shards (e.g.
    ``models.gpt2.block_tp_apply``); ``tp_col``/``tp_row`` name the column-/row-parallel
    sublayers so :meth:`PipelineModule.param_specs` can emit the matching physical
    sharding. Layers without a factory run replicated over any tensor axis.
    """

    def __init__(self, module, deterministic_kwarg: bool = False,
                 tp_apply_factory=None, tp_col: tuple = (), tp_row: tuple = (),
                 sp_apply_factory=None):
        self.module = module
        self.deterministic_kwarg = deterministic_kwarg
        self.tp_apply_factory = tp_apply_factory
        self.tp_col = tuple(tp_col)
        self.tp_row = tuple(tp_row)
        # seq-parallel forward: sp_apply_factory(sp, axis) returns a ring-local
        # layer fn consuming SEQUENCE-SHARDED activations (pipe×seq 1F1B bodies)
        self.sp_apply_factory = sp_apply_factory

    def _kwargs(self, rng):
        return {"deterministic": rng is None} if self.deterministic_kwarg else {}

    def init(self, rng, x):
        rngs = {"params": rng, "dropout": rng}
        return self.module.init(rngs, x, **self._kwargs(rng))["params"]

    def apply(self, params, x, rng=None):
        rngs = {"dropout": rng} if rng is not None else {}
        return self.module.apply({"params": params}, x, rngs=rngs, **self._kwargs(rng))


class LayerSpec:
    """Deferred layer construction (reference ``module.py:26``) — lets huge models describe
    themselves without materialising parameters until partitioning is known."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self) -> PipeLayer:
        obj = self.typename(*self.module_args, **self.module_kwargs)
        return _as_pipe_layer(obj)


class TiedLayerSpec(LayerSpec):
    """Layer sharing parameters with every other tied layer of the same ``key``
    (reference ``module.py:74``)."""

    def __init__(self, key, typename, *module_args, forward_fn=None, **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn

    def build(self) -> PipeLayer:
        layer = super().build()
        if self.forward_fn is not None:
            fwd = self.forward_fn
            base = layer

            class _TiedForward(PipeLayer):
                def init(self, rng, x):
                    return base.init(rng, x)

                def apply(self, params, x, rng=None):
                    return fwd(base, params, x)

            return _TiedForward()
        return layer


def _as_pipe_layer(obj) -> PipeLayer:
    if isinstance(obj, PipeLayer):
        return obj
    if callable(obj) and not hasattr(obj, "init"):
        return LambdaLayer(obj)
    if hasattr(obj, "apply") and hasattr(obj, "init"):  # flax module duck-type
        return FlaxPipeLayer(obj)
    raise TypeError(f"Cannot adapt {obj!r} to a pipeline layer")


def _split_batch(batch):
    """(inputs, labels) from the accepted batch forms — shared by every pipeline path."""
    if isinstance(batch, (tuple, list)) and len(batch) == 2:
        return batch[0], batch[1]
    if isinstance(batch, dict):
        return batch["inputs"], batch.get("labels")
    return batch, None


def partition_weights(layers: Sequence, abstract_params: Sequence,
                      method: str) -> List[float]:
    """Per-layer weights for stage balancing (reference ``module.py:_partition_layers``
    methods): ``uniform``, ``parameters``, or ``type:<regex>``. Shared by
    :class:`PipelineModule` and the eager executor."""
    method = method.lower()
    if method == "uniform":
        return [1.0] * len(layers)
    if method == "parameters":
        return [float(sum(int(np.prod(l.shape))
                          for l in jax.tree_util.tree_leaves(p))) or 1.0
                for p in abstract_params]
    if method.startswith("type:"):
        pat = re.compile(method[len("type:"):], re.IGNORECASE)
        return [1.0 if pat.search(type(layer).__name__) else 0.0
                for layer in layers]
    raise NotImplementedError(f"partition_method {method!r}")


# --------------------------------------------------------------------------- partitioning
def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Split ``weights`` into ``num_parts`` contiguous parts minimising the heaviest part.

    Returns part boundaries of length ``num_parts + 1`` (reference
    ``deepspeed/runtime/utils.py:partition_balanced`` used by ``module.py:_partition_layers``).
    Classic binary search over the bottleneck value.
    """
    n = len(weights)
    prefix = np.concatenate([[0.0], np.cumsum(weights)])

    def parts_needed(limit: float) -> Optional[List[int]]:
        bounds = [0]
        start = 0
        for _ in range(num_parts):
            # furthest end such that sum(start:end) <= limit
            end = int(np.searchsorted(prefix, prefix[start] + limit, side="right")) - 1
            if end <= start and start < n:
                end = start + 1  # always make progress (single item exceeds limit)
            end = min(end, n)
            bounds.append(end)
            start = end
        return bounds if bounds[-1] >= n else None

    lo, hi = float(max(weights) if len(weights) else 0.0), float(prefix[-1])
    for _ in range(64):
        mid = (lo + hi) / 2
        if parts_needed(mid) is not None:
            hi = mid
        else:
            lo = mid
    bounds = parts_needed(hi)
    bounds[-1] = n
    return bounds


# --------------------------------------------------------------------------- module
class PipelineModule:
    """See module docstring. Public surface mirrors reference ``PipelineModule:88``."""

    def __init__(self,
                 layers: Sequence,
                 num_stages: Optional[int] = None,
                 topology=None,
                 loss_fn: Optional[Callable] = None,
                 sample_input=None,
                 partition_method: str = "uniform",
                 activation_checkpoint_interval: int = 0,
                 aux_loss_coef: float = 0.0,
                 sp_loss_fn=None,
                 seed: int = 1234):
        if num_stages is None and topology is None:
            raise RuntimeError("must provide num_stages or topology")
        if topology is not None and num_stages is None:
            num_stages = topology.get_dim("pipe")
        self.num_stages = int(num_stages)
        self.topology = topology
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        # weight of body layers' auxiliary losses (MoE load balancing) in the total
        self.aux_loss_coef = float(aux_loss_coef)
        # sp_loss_fn(out_local, lab_local, axis_name): sequence-sharded tail loss
        # (psums its sum/count over the seq axis) — required for sp 1F1B
        self.sp_loss_fn = sp_loss_fn
        # optional post-processing of reference_apply's output in to_model's
        # apply_fn (keeps the logits contract when the head emits something else)
        self.apply_transform = None
        self.seed = seed
        if not (sample_input is not None):
            raise AssertionError("PipelineModule needs sample_input (abstract is fine) to trace layer shapes")
        self.sample_input = sample_input

        self._specs = list(layers)
        self._layers: List[PipeLayer] = []
        self._tied_keys: List[Optional[str]] = []
        for spec in self._specs:
            if isinstance(spec, LayerSpec):
                self._layers.append(spec.build())
                self._tied_keys.append(getattr(spec, "key", None))
            else:
                self._layers.append(_as_pipe_layer(spec))
                self._tied_keys.append(None)

        self._trace_structure()

    # ------------------------------------------------------------------ tracing
    def _trace_structure(self):
        """eval_shape every layer on the propagated sample activation; find the homogeneous
        body run; compute stage boundaries."""
        rng = jax.random.PRNGKey(self.seed)
        x = self.sample_input
        shapes = []   # (treedef_repr, leaf shapes) per layer
        self._abstract_params: List[Any] = []
        tied_abstract: Dict[str, Any] = {}
        for i, layer in enumerate(self._layers):
            key = self._tied_keys[i]
            if key is not None and key in tied_abstract:
                p = tied_abstract[key]
            else:
                p = jax.eval_shape(partial(layer.init), rng, x)
                if key is not None:
                    tied_abstract[key] = p
            self._abstract_params.append(p)
            leaves = jax.tree_util.tree_leaves(p)
            # signature includes layer IDENTITY (type + wrapped-module repr), not just param
            # shapes: two different layer types with coincidentally equal param trees must
            # not be merged into one body and applied with the first layer's apply()
            ident = type(layer).__name__
            inner = getattr(layer, "module", None)
            if inner is not None:
                ident += ":" + repr(inner)
            sig = (ident,
                   str(jax.tree_util.tree_structure(p)),
                   tuple((tuple(l.shape), str(l.dtype)) for l in leaves))
            shapes.append(sig)
            x = jax.eval_shape(partial(layer.apply), p, x, None)
        self._output_shape = x

        # longest homogeneous run of layers with parameters
        best = (0, 0)  # (start, length)
        i = 0
        n = len(self._layers)
        while i < n:
            # tied layers can never join the body: their params live in params['tied'] and
            # stacking a copy into params['body'] would silently untie the weights
            if (not jax.tree_util.tree_leaves(self._abstract_params[i])
                    or self._tied_keys[i] is not None):
                i += 1
                continue
            j = i + 1
            while j < n and shapes[j] == shapes[i] and self._tied_keys[j] is None:
                j += 1
            if j - i > best[1]:
                best = (i, j - i)
            i = j
        start, length = best
        S = self.num_stages
        if length < S:
            raise ValueError(
                f"Pipeline needs a homogeneous block run >= num_stages: found {length} "
                f"homogeneous layers for {S} stages")
        # trim the run so the body length divides num_stages; spill extras to pre/post
        spill = length % S
        start += spill  # keep early layers (closer to embeddings) in pre
        length -= spill
        self.body_start = start
        self.body_end = start + length
        self.layers_per_stage = length // S
        if spill:
            logger.info(f"PipelineModule: spilled {spill} block(s) to the pre segment so "
                        f"{length} body layers divide {S} stages")

        self.parts = self._compute_parts()

    def _compute_parts(self) -> List[int]:
        """Stage boundaries over the full layer list (reference ``_partition_layers:367``) —
        informational/ckpt-naming; the SPMD executor uses the body stacking above."""
        weights = partition_weights(self._layers, self._abstract_params,
                                    self.partition_method)
        return partition_balanced(weights, self.num_stages)

    # ------------------------------------------------------------------ params
    def init_fn(self, rng):
        """Build the structured param tree: pre/body(stacked)/post/tied."""
        params = {"pre": {}, "body": None, "post": {}, "tied": {}}
        x_abs = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), self.sample_input)
        body_stack: List[Any] = []
        for i, layer in enumerate(self._layers):
            lrng = jax.random.fold_in(rng, i)
            key = self._tied_keys[i]
            x_dummy = jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape, l.dtype), x_abs)
            if key is not None and key in params["tied"]:
                p = params["tied"][key]
            else:
                p = layer.init(lrng, x_dummy)
                if key is not None:
                    params["tied"][key] = p
            if self.body_start <= i < self.body_end:
                body_stack.append(p)
            elif key is None and jax.tree_util.tree_leaves(p):
                seg = "pre" if i < self.body_start else "post"
                params[seg][str(i)] = p
            x_abs = jax.eval_shape(partial(layer.apply), _abstract(p), x_abs, None)
        params["body"] = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *body_stack)
        return params

    def param_specs(self, abstract_params=None, tp_axis: Optional[str] = None,
                    tp_size: Optional[int] = None,
                    ep_size: Optional[int] = None) -> Any:
        """PartitionSpec tree: body stacked dim shards over ``pipe``; rest replicated.

        With ``tp_axis``, body weights shard per the body layer's Megatron
        classification (``FlaxPipeLayer.tp_col``/``tp_row``): column-parallel kernels
        and biases shard their LAST dim, row-parallel kernels their first weight dim
        (bias replicated). This is the PHYSICAL layout the 1F1B shard_map's
        manual-collective stage_fn consumes (see :meth:`make_1f1b_loss_fn`). Layers
        without tp rules fall back to naive last-dim sharding of ndim>=3 leaves
        (GSPMD-correct for non-shard_map executors, may insert reshards).
        ``tp_size`` defaults to the global mesh's axis size."""
        if abstract_params is None:
            abstract_params = jax.eval_shape(self.init_fn, jax.random.PRNGKey(0))
        from ...parallel.mesh import get_global_mesh
        if tp_axis and tp_size is None:
            mesh = get_global_mesh()
            tp_size = mesh.size(tp_axis) if mesh is not None else 1
        if ep_size is None or ep_size < 1:   # None/-1 = unresolved ("infer")
            gmesh = get_global_mesh()
            ep_size = gmesh.size(AXIS_EXPERT) if gmesh is not None else 1
        body_layer = self._layers[self.body_start]
        tp_col = tuple(getattr(body_layer, "tp_col", ()))
        tp_row = tuple(getattr(body_layer, "tp_row", ()))
        ep_paths = tuple(getattr(body_layer, "ep_paths", ()))
        use_rules = bool(tp_axis and tp_size and tp_size > 1 and (tp_col or tp_row))

        def body_spec_by_path(path, leaf):
            entries = [AXIS_PIPE] + [None] * (leaf.ndim - 1)
            names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
            if ep_paths and any(n in ep_paths for n in names):
                # expert-stacked leaf (L_per, e, ...): expert dim over the expert
                # axis (reference expert-parallel groups, utils/groups.py:109);
                # non-divisible expert counts replicate, like the TP rules
                if leaf.ndim >= 2 and ep_size > 1 and \
                        leaf.shape[1] % ep_size == 0:
                    entries[1] = AXIS_EXPERT
                return P(*entries)
            parent = names[-2] if len(names) >= 2 else ""
            kind = names[-1] if names else ""
            if use_rules and parent in tp_col and leaf.shape[-1] % tp_size == 0:
                entries[-1] = tp_axis                     # kernel AND bias follow cols
            elif use_rules and parent in tp_row and kind == "kernel" \
                    and leaf.ndim >= 3 and leaf.shape[1] % tp_size == 0:
                entries[1] = tp_axis                      # first weight dim (inputs)
            elif not use_rules and tp_axis and leaf.ndim >= 3 and tp_size \
                    and tp_size > 1 and leaf.shape[-1] % tp_size == 0:
                entries[-1] = tp_axis                     # generic last-dim fallback
            return P(*entries)

        def seg_spec(seg_name):
            def one(leaf):
                return P(*([None] * leaf.ndim))
            return one

        out = {}
        for seg in ("pre", "body", "post", "tied"):
            if seg == "body":
                out[seg] = jax.tree_util.tree_map_with_path(
                    body_spec_by_path, abstract_params[seg])
            else:
                out[seg] = jax.tree_util.tree_map(seg_spec(seg),
                                                  abstract_params[seg])
        return out

    # ------------------------------------------------------------------ forward paths
    def _segment_apply(self, params, x, rng, lo, hi):
        """Apply layers [lo, hi) sequentially (non-body segments + reference executor)."""
        for i in range(lo, hi):
            if self.body_start <= i < self.body_end:
                continue
            layer = self._layers[i]
            key = self._tied_keys[i]
            p = (params["tied"][key] if key is not None
                 else params.get("pre", {}).get(str(i),
                      params.get("post", {}).get(str(i), {})))
            lrng = None if rng is None else jax.random.fold_in(rng, i)
            x = layer.apply(p, x, lrng)
        return x

    def reference_apply(self, params, x, rng=None):
        """Sequential (non-pipelined) forward — ground truth for tests and single-stage."""
        body_layer = self._layers[self.body_start]
        x = self._segment_apply(params, x, rng, 0, self.body_start)

        def body_one(carry, xs):
            p, r = xs
            return body_layer.apply(p, carry, None if rng is None else r), None

        n_body = self.body_end - self.body_start
        rngs = (jax.random.split(jax.random.fold_in(rng, 10**6), n_body)
                if rng is not None else jnp.zeros((n_body, 2), dtype=jnp.uint32))
        x, _ = jax.lax.scan(body_one, x, (params["body"], rngs))
        return self._segment_apply(params, x, rng, self.body_end, len(self._layers))

    def pipelined_apply(self, params, xs, mesh_spec: MeshSpec, rng=None,
                        remat: bool = True):
        """GPipe fill-drain loop over the ``pipe`` axis.

        ``xs``: microbatched activations entering the body, shape ``(M, mb, ...)``.
        Returns body outputs ``(M, mb, ...)``.
        """
        S = self.num_stages
        L_per = self.layers_per_stage
        body_layer = self._layers[self.body_start]
        M = xs.shape[0]
        if rng is None:
            rng = jax.random.PRNGKey(0)
            use_rng = False
        else:
            use_rng = True

        def stage_fn(stage_params, x, srng):
            def one(carry, xs_):
                p, r = xs_
                return body_layer.apply(p, carry, r if use_rng else None), None

            rngs = jax.random.split(srng, L_per)
            x, _ = jax.lax.scan(one, x, (stage_params, rngs))
            return x

        if remat:
            stage_fn = jax.checkpoint(stage_fn)

        n_iters = M + S - 1

        def run(body_params, xs_local, rng_in):
            stage = jax.lax.axis_index(AXIS_PIPE)
            recv0 = jnp.zeros_like(xs_local[0])
            outs0 = jnp.zeros_like(xs_local)

            def step(carry, t):
                recv, outs = carry
                x_in = jnp.where(stage == 0,
                                 jax.lax.dynamic_index_in_dim(
                                     xs_local, jnp.clip(t, 0, M - 1), 0, keepdims=False),
                                 recv)
                srng = jax.random.fold_in(jax.random.fold_in(rng_in, t), stage)
                y = stage_fn(body_params, x_in, srng)
                m = t - stage
                valid = jnp.logical_and(m >= 0, m < M)
                m_c = jnp.clip(m, 0, M - 1)
                outs = jnp.where(
                    valid,
                    jax.lax.dynamic_update_index_in_dim(outs, y, m_c, 0),
                    outs)
                recv_next = jax.lax.ppermute(
                    y, AXIS_PIPE, [(i, i + 1) for i in range(S - 1)])
                return (recv_next, outs), None

            (_, outs), _ = jax.lax.scan(step, (recv0, outs0), jnp.arange(n_iters))
            return outs[None]  # local (1, M, ...) → stacked (S, M, ...) outside

        if S == 1:
            return jax.vmap(lambda x, r: stage_fn(params["body"], x, r))(
                xs, jax.random.split(rng, M))

        mapped = shard_map(
            run,
            mesh=mesh_spec.mesh,
            axis_names={AXIS_PIPE},
            in_specs=(P(AXIS_PIPE), P(), P()),
            out_specs=P(AXIS_PIPE),
            check_vma=False,
            # NOTE on old jax (no jax.shard_map): the shim runs fully manual —
            # data/expert stay replicated through the region (values identical,
            # redundant compute); expert-sharded MoE pipe bodies need true
            # partial-auto and are unsupported there (fail loudly at trace)
        )
        stacked = mapped(params["body"], xs, rng)  # (S, M, mb, ...)
        return stacked[S - 1]

    # ------------------------------------------------------------------ 1F1B
    def make_1f1b_loss_fn(self, mesh_spec: Optional[MeshSpec] = None,
                          tp_axis: Optional[str] = None,
                          aux_loss_coef: Optional[float] = None,
                          sp_axis: Optional[str] = None):
        """Interleaved 1F1B with manual in-loop backward — O(stages) activation memory.

        Reference semantics: ``runtime/pipe/engine.py:295`` executing
        ``schedule.py:TrainSchedule`` (warmup forwards, steady-state one-forward-one-
        backward, drain). The SPMD realisation runs one lockstep ``lax.scan`` over
        ``2(M+S)-3`` ticks; at tick ``t`` stage ``s`` forwards microbatch ``(t-s)/2`` and
        backwards microbatch ``(t-(2S-2-s))/2`` (both when valid — steady-state ticks do
        one of each, the 1F1B alternation). Activations cross stages by ``ppermute``;
        cotangents ride the reverse permute one tick behind.

        Unlike the GPipe path (autodiff through the fill-drain loop, which stores an
        O(M) boundary-activation residual per stage), gradients here are computed *inside*
        the loop: each stage keeps a circular stash of its last ``S`` microbatch inputs and,
        on a backward tick, re-plays its block run under ``jax.vjp`` (per-microbatch remat
        — the 2× forward cost every 1F1B implementation pays via activation checkpointing)
        and folds parameter cotangents into fp32 accumulators carried by the scan. Nothing
        autodiffs *through* the scan, so peak activation memory is the stash — O(S·mb),
        flat in M (verified by ``test_1f1b_memory_flat_in_microbatches``).

        The pre segment (embeddings) runs on stage 0 *inside* its forward tick and the
        post segment + loss on the last stage inside its tick, so no O(M) staging buffer
        exists anywhere. Tied parameters may be consumed by both segments; their two
        cotangent streams meet in the cross-stage ``psum`` (the reference's
        ``ReduceTiedGrads``).

        With ``tp_axis``, the shard_map goes manual over {pipe, tensor}: body weights
        are PHYSICALLY sharded per the layer's Megatron col/row rules and the stage_fn
        is the layer's manual-collective ``tp_apply_factory`` forward (explicit psum
        after each row-parallel matmul) — reference 3D parallelism with TP inside
        pipeline stages (``runtime/pipe/topology.py:243``). Activations (and the
        pre/post/tied segments) replicate over tensor; their VJPs produce identical
        cotangents on every tensor shard.

        Returns ``fn(params, batch, rng) -> loss`` wrapped in ``jax.custom_vjp`` whose
        forward pass also produces the full parameter gradient (the engine's
        ``value_and_grad`` triggers exactly one loop execution).
        """
        S = self.num_stages
        L_per = self.layers_per_stage
        body_layer = self._layers[self.body_start]
        n_layers = len(self._layers)
        # MoE body layers emit an auxiliary load-balancing scalar per layer; it is
        # summed over layers in the stage scan, over stages in the final pipe psum,
        # and over microbatches in the loss accumulator — then weighted by
        # aux_loss_coef. Dense layers emit 0.0 (DCE'd by XLA).
        body_aux = bool(getattr(body_layer, "has_aux", False))
        if aux_loss_coef is None:
            aux_loss_coef = self.aux_loss_coef
        aux_coef = jnp.float32(aux_loss_coef)

        split_batch = _split_batch

        def pre_apply(pre_p, tied_p, x, mrng):
            view = {"pre": pre_p, "post": {}, "tied": tied_p}
            return self._segment_apply(view, x, mrng, 0, self.body_start)

        def tail_loss(post_p, tied_p, y, lab, mrng, sp=1):
            view = {"pre": {}, "post": post_p, "tied": tied_p}
            out = self._segment_apply(view, y, mrng, self.body_end, n_layers)
            if sp > 1:
                # sequence-sharded tail: per-shard loss contributions reduce to
                # the global mean via psum inside sp_loss_fn (sum/count over the
                # seq axis — unequal valid-token counts per shard stay exact)
                if not (self.sp_loss_fn is not None):
                    raise AssertionError("seq-parallel 1F1B needs PipelineModule.sp_loss_fn")
                return self.sp_loss_fn(out, lab, sp_axis)
            if self.loss_fn is not None:
                return self.loss_fn(out, lab)
            return out if out.ndim == 0 else jnp.mean(out)

        tp_fns = {}   # tp degree -> manual-collective layer forward (built lazily)
        sp_fns = {}   # sp degree -> ring-local layer forward (built lazily)

        def _layer_apply(tp, sp=1):
            if sp > 1 and sp_axis is not None:
                # pipe×seq: activations are sequence-sharded inside the stage;
                # attention all-gathers K/V over the seq axis (GROUPED collective
                # — a ppermute ring under the pipe-staggered conds is undefined,
                # see ops/attention/ring.py:allgather_attention_local)
                if not (not body_aux):
                    raise AssertionError("seq parallelism inside 1F1B does not compose with " \
                    "aux-loss (MoE) bodies yet")
                key = (tp, sp)
                if key not in sp_fns:
                    if tp > 1 and tp_axis is not None:
                        # pipe×tensor×seq 4D: the TP block with seq-sharded
                        # activations — dense/LN are per-token, only attention
                        # changes (local heads over seq-gathered K/V)
                        import inspect
                        factory = getattr(body_layer, "tp_apply_factory", None)
                        if not (factory is not None):
                            raise AssertionError("pipe×tensor×seq needs a body tp_apply_factory")
                        sig = inspect.signature(factory)
                        if not ("sp_axis" in sig.parameters or any(
                            p.kind == inspect.Parameter.VAR_KEYWORD
                            for p in sig.parameters.values())):
                            raise AssertionError("the body's tp_apply_factory does not accept "
                             "sp_axis — pipe×tensor×seq needs one that does "
                             "(e.g. gpt2 blocks, models/gpt2.py:block_tp_apply)")
                        sp_fns[key] = factory(tp, tp_axis, sp_axis=sp_axis)
                    else:
                        factory = getattr(body_layer, "sp_apply_factory", None)
                        if not (factory is not None):
                            raise AssertionError("sequence parallelism inside the 1F1B pipeline "
                             "needs a body layer with sp_apply_factory (e.g. "
                             "gpt2_pipe blocks with GPT2Config(split_qkv=True))")
                        sp_fns[key] = factory(sp, sp_axis)
                fn = sp_fns[key]
                return lambda p, x, r: (fn(p, x, r), jnp.float32(0.0))
            if tp <= 1 or tp_axis is None:
                if body_aux:
                    return lambda p, x, r: body_layer.apply_with_aux(p, x, r)
                return lambda p, x, r: (body_layer.apply(p, x, r),
                                        jnp.float32(0.0))
            if not (not body_aux):
                raise AssertionError("in-stage tensor parallelism and aux-loss (MoE) body layers are "
                 "not composed yet — run MoE pipelines with tp_axis=None and "
                 "shard experts over the expert axis instead")
            if tp not in tp_fns:
                factory = getattr(body_layer, "tp_apply_factory", None)
                if not (factory is not None):
                    raise AssertionError("tensor parallelism inside the 1F1B pipeline needs a body layer "
                     "with tp_apply_factory (e.g. gpt2_pipe blocks with "
                     "split_qkv=True)")
                tp_fns[tp] = factory(tp, tp_axis)
            fn = tp_fns[tp]
            return lambda p, x, r: (fn(p, x, r), jnp.float32(0.0))

        def make_stage_fn(tp, sp=1):
            layer_fn = _layer_apply(tp, sp)

            def stage_fn(stage_params, x, srng, use_rng):
                def one(carry, xs_):
                    p, r = xs_
                    y, aux = layer_fn(p, carry, r if use_rng else None)
                    return y, aux

                rngs = jax.random.split(srng, L_per)
                y, auxs = jax.lax.scan(one, x, (stage_params, rngs))
                return y, jnp.sum(auxs).astype(jnp.float32)
            return stage_fn

        def idx(tree, m):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False), tree)

        def tree_add(acc, new):
            return jax.tree_util.tree_map(jnp.add, acc, new)

        def f32_cast(tree):
            return jax.tree_util.tree_map(
                lambda x: x.astype(jnp.float32), tree)

        def f32_zeros(tree):
            return jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), tree)

        def run_1f1b(params, batch, rng, use_rng: bool):
            mesh = mesh_spec or _require_global_mesh()
            tp = mesh.size(tp_axis) if tp_axis else 1
            sp = mesh.size(sp_axis) if sp_axis else 1
            stage_fn = make_stage_fn(tp, sp)
            inputs, labels = split_batch(batch)
            M = jax.tree_util.tree_leaves(inputs)[0].shape[0]
            n_ticks = 2 * (M + S) - 3
            rng_pre = jax.random.fold_in(rng, 1)
            rng_body = jax.random.fold_in(rng, 2)
            rng_tail = jax.random.fold_in(rng, 3)

            def run(body_p, pre_p, post_p, tied_p, inputs_, labels_):
                s = jax.lax.axis_index(AXIS_PIPE)

                # trace one pre output to size the activation stash
                x0_shape = jax.eval_shape(
                    pre_apply, _abstract(pre_p), _abstract(tied_p),
                    _abstract(idx(inputs_, 0)), rng_pre)
                # pipe×seq: the PRE segment runs on FULL sequences (embeddings
                # are cheap and position-offset-free); the BODY and TAIL carry
                # t/sp local chunks (tail loss reduces via sp_loss_fn's psum) —
                # stash, recv buffers and cross-stage permutes all shrink by sp,
                # and attention all-gathers K/V over the seq axis
                if sp > 1:
                    t_full = x0_shape.shape[1]
                    if not (t_full % sp == 0):
                        raise AssertionError((t_full, sp))
                    tl_sp = t_full // sp
                    s_sp = jax.lax.axis_index(sp_axis)
                    body_shape = (x0_shape.shape[0], tl_sp) + \
                        tuple(x0_shape.shape[2:])

                    def to_local(x_full):
                        return jax.lax.dynamic_slice_in_dim(
                            x_full, s_sp * tl_sp, tl_sp, axis=1)

                    def to_full_cot(dx_local):
                        zeros = jnp.zeros(tuple(x0_shape.shape),
                                          dx_local.dtype)
                        return jax.lax.dynamic_update_slice_in_dim(
                            zeros, dx_local, s_sp * tl_sp, axis=1)
                else:
                    body_shape = tuple(x0_shape.shape)
                    to_local = to_full_cot = lambda x: x
                stash0 = jnp.zeros((S,) + body_shape, x0_shape.dtype)

                carry0 = dict(
                    recv_f=jnp.zeros(body_shape, x0_shape.dtype),
                    recv_b=jnp.zeros(body_shape, x0_shape.dtype),
                    stash=stash0,
                    loss=jnp.float32(0.0),
                    dbody=f32_zeros(body_p),
                    dpre=f32_zeros(pre_p),
                    dpost=f32_zeros(post_p),
                    dtied=f32_zeros(tied_p),
                )

                def tick(carry, t):
                    # Every phase sits behind lax.cond on its validity predicate: for a
                    # given stage, forward ticks (t-s even) and backward ticks
                    # (t-(2S-2-s) even) share parity, so half of all ticks are no-ops —
                    # cond (not jnp.where-after-compute) lets XLA skip them, and the
                    # tail/pre VJPs additionally run only on the stage that keeps them.
                    last = s == S - 1
                    # ---------------- forward phase -----------------------------
                    mf_raw = t - s
                    is_f = (mf_raw >= 0) & (mf_raw % 2 == 0) & (mf_raw // 2 < M)
                    mf = jnp.clip(mf_raw // 2, 0, M - 1)

                    def fwd_block(stash_in, recv_f):
                        x0 = pre_apply(
                            pre_p, tied_p, idx(inputs_, mf),
                            jax.random.fold_in(rng_pre, mf) if use_rng else None)
                        x_in = jnp.where(s == 0, to_local(x0), recv_f)
                        y, aux = stage_fn(
                            body_p, x_in,
                            jax.random.fold_in(jax.random.fold_in(rng_body, mf), s),
                            use_rng)
                        return y, jax.lax.dynamic_update_index_in_dim(
                            stash_in, x_in, mf % S, 0), aux

                    def fwd_skip(stash_in, recv_f):
                        return jnp.zeros_like(recv_f), stash_in, jnp.float32(0.0)

                    y, stash, aux_m = jax.lax.cond(is_f, fwd_block, fwd_skip,
                                                   carry["stash"], carry["recv_f"])

                    def tail_block(y_):
                        lab_m = idx(labels_, mf) if labels_ is not None else None
                        if sp > 1 and lab_m is not None:
                            lab_m = jax.tree_util.tree_map(
                                lambda a: jax.lax.dynamic_slice_in_dim(
                                    a, s_sp * tl_sp, tl_sp, axis=1), lab_m)
                        loss_m, tail_vjp = jax.vjp(
                            lambda po, ti, yy: tail_loss(
                                po, ti, yy, lab_m,
                                jax.random.fold_in(rng_tail, mf) if use_rng
                                else None, sp=sp),
                            post_p, tied_p, y_)
                        dpost_m, dtied_m, dy_m = tail_vjp(jnp.float32(1.0))
                        return (loss_m.astype(jnp.float32), f32_cast(dpost_m),
                                f32_cast(dtied_m), dy_m.astype(y_.dtype))

                    def tail_skip(y_):
                        return (jnp.float32(0.0), f32_zeros(post_p),
                                f32_zeros(tied_p), jnp.zeros_like(y_))

                    loss_m, dpost_m, dtied_tail_m, dy_m = jax.lax.cond(
                        is_f & last, tail_block, tail_skip, y)
                    # every stage contributes its own layers' aux on its forward tick
                    loss = carry["loss"] + loss_m + aux_coef * aux_m
                    dpost = tree_add(carry["dpost"], dpost_m)
                    dtied = tree_add(carry["dtied"], dtied_tail_m)

                    # ---------------- backward phase ----------------------------
                    mb_raw = t - (2 * S - 2 - s)
                    is_b = (mb_raw >= 0) & (mb_raw % 2 == 0) & (mb_raw // 2 < M)
                    mb = jnp.clip(mb_raw // 2, 0, M - 1)
                    cot = jnp.where(last, dy_m, carry["recv_b"])

                    def bwd_block(stash_in, cot_):
                        x_saved = jax.lax.dynamic_index_in_dim(stash_in, mb % S, 0,
                                                               keepdims=False)
                        _, svjp = jax.vjp(
                            lambda bp, xx: stage_fn(
                                bp, xx,
                                jax.random.fold_in(jax.random.fold_in(rng_body, mb), s),
                                use_rng),
                            body_p, x_saved)
                        # aux output's cotangent is its loss weight: gate/expert
                        # params receive the load-balancing gradient here
                        dbody_m, dx = svjp((cot_, aux_coef))
                        return f32_cast(dbody_m), dx.astype(cot_.dtype)

                    def bwd_skip(stash_in, cot_):
                        return f32_zeros(body_p), jnp.zeros_like(cot_)

                    dbody_m, dx = jax.lax.cond(is_b, bwd_block, bwd_skip, stash, cot)
                    dbody = tree_add(carry["dbody"], dbody_m)

                    def pre_block(dx_):
                        # stage 0 re-plays the pre segment to push dx into embeddings/tied
                        # (sp: scatter the LOCAL chunk's cotangent into the full-
                        # sequence zeros — other chunks contribute via the sp psum)
                        _, pvjp = jax.vjp(
                            lambda pr, ti: pre_apply(
                                pr, ti, idx(inputs_, mb),
                                jax.random.fold_in(rng_pre, mb) if use_rng else None),
                            pre_p, tied_p)
                        dpre_m, dtied_m = pvjp(to_full_cot(dx_))
                        return f32_cast(dpre_m), f32_cast(dtied_m)

                    def pre_skip(dx_):
                        return f32_zeros(pre_p), f32_zeros(tied_p)

                    dpre_m, dtied_pre_m = jax.lax.cond(is_b & (s == 0),
                                                       pre_block, pre_skip, dx)
                    dpre = tree_add(carry["dpre"], dpre_m)
                    dtied = tree_add(dtied, dtied_pre_m)

                    new_carry = dict(
                        recv_f=jax.lax.ppermute(
                            y, AXIS_PIPE, [(i, i + 1) for i in range(S - 1)]),
                        recv_b=jax.lax.ppermute(
                            dx, AXIS_PIPE, [(i, i - 1) for i in range(1, S)]),
                        stash=stash, loss=loss, dbody=dbody, dpre=dpre,
                        dpost=dpost, dtied=dtied)
                    return new_carry, None

                out, _ = jax.lax.scan(tick, carry0, jnp.arange(n_ticks))
                inv_m = jnp.float32(1.0 / M)
                loss = jax.lax.psum(out["loss"] * inv_m, AXIS_PIPE)
                scale_tree = lambda tr: jax.tree_util.tree_map(
                    lambda g: g * inv_m, tr)
                # sp: pre/post/tied/body grads are per-shard partials (each seq
                # shard differentiated only its tokens' contribution) — sum them
                repl_axes = (AXIS_PIPE, sp_axis) if sp > 1 else AXIS_PIPE
                dpre = jax.lax.psum(scale_tree(out["dpre"]), repl_axes)
                dpost = jax.lax.psum(scale_tree(out["dpost"]), repl_axes)
                dtied = jax.lax.psum(scale_tree(out["dtied"]), repl_axes)
                dbody = scale_tree(out["dbody"])
                if sp > 1:
                    dbody = jax.lax.psum(dbody, sp_axis)
                return loss, dbody, dpre, dpost, dtied

            lab_spec = None if labels is None else P()
            if tp > 1:
                body_specs = self.param_specs(tp_axis=tp_axis, tp_size=tp)["body"]
                manual_axes = {AXIS_PIPE, tp_axis}
            else:
                body_specs = P(AXIS_PIPE)
                manual_axes = {AXIS_PIPE}
            if sp > 1:
                manual_axes = manual_axes | {sp_axis}
            mapped = shard_map(
                run,
                mesh=mesh.mesh,
                axis_names=manual_axes,
                in_specs=(body_specs, P(), P(), P(), P(), lab_spec),
                out_specs=(P(), body_specs, P(), P(), P()),
                check_vma=False,
            )
            loss, dbody, dpre, dpost, dtied = mapped(
                params["body"], params["pre"], params["post"], params["tied"],
                inputs, labels)
            grads = {"body": dbody, "pre": dpre, "post": dpost, "tied": dtied}
            grads = jax.tree_util.tree_map(
                lambda g, p: g.astype(p.dtype), grads,
                {"body": params["body"], "pre": params["pre"],
                 "post": params["post"], "tied": params["tied"]})
            return loss, grads

        @jax.custom_vjp
        def pipe_loss(params, batch, rng):
            loss, _ = run_1f1b(params, batch, rng, use_rng=True)
            return loss

        def pipe_loss_fwd(params, batch, rng):
            loss, grads = run_1f1b(params, batch, rng, use_rng=True)
            return loss, (grads, batch, rng)

        def pipe_loss_bwd(res, g):
            grads, batch, rng = res
            dparams = jax.tree_util.tree_map(lambda x: (x * g).astype(x.dtype), grads)
            return dparams, _zero_cotangent(batch), _zero_cotangent(rng)

        pipe_loss.defvjp(pipe_loss_fwd, pipe_loss_bwd)
        return pipe_loss

    # ------------------------------------------------------------------ model adapter
    def to_model(self, mesh_spec: Optional[MeshSpec] = None, name: str = "pipeline",
                 remat: Optional[bool] = None, schedule: str = "1f1b",
                 tp_axis: Optional[str] = None, tp_size: Optional[int] = None,
                 ep_size: Optional[int] = None, sp_axis: Optional[str] = None):
        """Bundle into the engine's :class:`Model` contract. ``loss_fn`` consumes microbatched
        batches ``(inputs, labels)`` with leading dim M and returns mean loss; ``rng=None``
        runs a deterministic (dropout-off) pass.

        ``schedule``: ``"1f1b"`` (default) trains through the interleaved
        one-forward-one-backward loop with in-loop gradients — O(stages) activation
        memory (see :meth:`make_1f1b_loss_fn`); ``"gpipe"`` trains by autodiff through
        the fill-drain loop (O(microbatches) boundary residuals, no recompute). Eval
        always uses the forward-only fill-drain pipeline.
        """
        # imported here, not at module top: models/__init__ imports gpt2_pipe which imports
        # this module — a top-level import would make the cycle order-dependent
        from ...models.base import Model
        if remat is None:
            remat = self.activation_checkpoint_interval > 0
        if not (schedule in ("1f1b", "gpipe")):
            raise AssertionError(schedule)
        body_has_aux = bool(getattr(self._layers[self.body_start], "has_aux",
                                    False))
        pipe_loss_1f1b = (self.make_1f1b_loss_fn(mesh_spec, tp_axis=tp_axis,
                                                 aux_loss_coef=self.aux_loss_coef,
                                                 sp_axis=sp_axis)
                          if schedule == "1f1b" and self.num_stages > 1 else None)
        if body_has_aux and pipe_loss_1f1b is None:
            raise NotImplementedError(
                "aux-loss (MoE) body layers train through the 1F1B schedule only "
                "(the fill-drain/GPipe loop does not aggregate aux losses) — use "
                "schedule='1f1b' with num_stages > 1")

        split_batch = _split_batch

        def loss_fn(params, batch, rng):
            mesh = mesh_spec or _require_global_mesh()
            inputs, labels = split_batch(batch)
            M = jax.tree_util.tree_leaves(inputs)[0].shape[0]
            if rng is None:  # deterministic pass (eval)
                if tp_axis is not None and mesh.size(tp_axis) > 1:
                    # TP body params are physically sharded; the fill-drain shard_map
                    # is pipe-manual-only and cannot consume them — evaluate via the
                    # sequential reference path under GSPMD auto-sharding instead
                    def eval_one(inp, lab):
                        out = self.reference_apply(params, inp, None)
                        if self.loss_fn is not None:
                            return self.loss_fn(out, lab)
                        return out if out.ndim == 0 else jnp.mean(out)

                    return jnp.mean(jax.vmap(eval_one)(inputs, labels))
                xs = jax.vmap(
                    lambda inp: self._segment_apply(params, inp, None, 0, self.body_start)
                )(inputs)
                ys = self.pipelined_apply(params, xs, mesh, rng=None, remat=remat)

                def tail_det(y, lab):
                    out = self._segment_apply(params, y, None, self.body_end,
                                              len(self._layers))
                    if self.loss_fn is not None:
                        return self.loss_fn(out, lab)
                    return out if out.ndim == 0 else jnp.mean(out)

                return jnp.mean(jax.vmap(tail_det)(ys, labels))

            if pipe_loss_1f1b is not None:
                return pipe_loss_1f1b(params, batch, rng)

            pre_rngs = jax.random.split(jax.random.fold_in(rng, 1), M)
            xs = jax.vmap(
                lambda inp, r: self._segment_apply(params, inp, r, 0, self.body_start)
            )(inputs, pre_rngs)
            ys = self.pipelined_apply(params, xs, mesh,
                                      rng=jax.random.fold_in(rng, 2), remat=remat)
            post_rngs = jax.random.split(jax.random.fold_in(rng, 3), M)

            def tail(y, lab, r):
                out = self._segment_apply(params, y, r, self.body_end, len(self._layers))
                if self.loss_fn is not None:
                    return self.loss_fn(out, lab)
                return out if out.ndim == 0 else jnp.mean(out)

            losses = jax.vmap(tail)(ys, labels, post_rngs)
            return jnp.mean(losses)

        def apply_fn(params, batch, rng=None):
            inputs, _ = split_batch(batch)
            out = self.reference_apply(params, inputs, rng)
            # builders whose head emits a non-logits payload (e.g. the chunked-
            # vocab (hidden, wte) tuple) install a transform so apply_fn keeps
            # the logits contract callers rely on
            if self.apply_transform is not None:
                out = self.apply_transform(out)
            return out

        return Model(loss_fn=loss_fn, init_fn=self.init_fn, apply_fn=apply_fn,
                     param_specs=self.param_specs(tp_axis=tp_axis, tp_size=tp_size,
                                                  ep_size=ep_size),
                     name=name)

    def __len__(self):
        return len(self._layers)


def _abstract(p):
    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), p)


def _zero_cotangent(tree):
    """Zero cotangents for a possibly-integer pytree (custom_vjp bwd for nondiff inputs):
    float leaves get zeros, integer leaves (tokens, PRNG keys) get float0."""
    def one(x):
        if jnp.issubdtype(jnp.result_type(x), jnp.inexact):
            return jnp.zeros_like(x)
        return np.zeros(jnp.shape(x), dtype=jax.dtypes.float0)
    return jax.tree_util.tree_map(one, tree)


def _require_global_mesh() -> MeshSpec:
    from ...parallel.mesh import get_global_mesh
    mesh = get_global_mesh()
    if not (mesh is not None):
        raise AssertionError("pipeline loss_fn needs a global mesh (set by the engine)")
    return mesh
