"""Pipeline module (LayerSpec/PipelineModule) — full implementation with the pipeline engine.

Reference: ``deepspeed/runtime/pipe/module.py`` (``LayerSpec:26``, ``PipelineModule:88``).
"""


class LayerSpec:
    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)


class PipelineModule:
    """Placeholder until runtime/pipe/engine.py lands (build-plan phase 5)."""

    def __init__(self, *args, **kwargs):
        raise NotImplementedError("PipelineModule arrives with the pipeline engine phase")
