"""Pipeline training engine.

Reference: ``deepspeed/runtime/pipe/engine.py`` (``PipelineEngine:37``, ``train_batch:295``,
``eval_batch:380``, ``_exec_schedule:1360``).

Where the reference interprets an instruction stream (``schedule.py``) with explicit P2P
send/recv per stage process, this engine compiles the whole pipelined batch into ONE jitted
step: the PipelineModule's collective-permute loop performs fill/steady/drain implicitly, and
autodiff through it yields the backward drain. ``train_batch()`` therefore has identical
semantics (gas microbatches → one optimizer step) with XLA scheduling the overlap.

Composes with the base engine's ZeRO sharding (over ``fsdp``), precision, checkpointing and
observability unchanged — the reference's "PipelineEngine is compatible with ZeRO-1 and bf16"
constraint does not apply here: any stage/precision combination compiles.
"""

from typing import Optional

import jax
import numpy as np

from ...config.config import DeepSpeedConfig
from ..engine import DeepSpeedEngine, TrainState
from ...utils.timer import TRAIN_BATCH_TIMER
from ...utils.logging import log_dist
from .module import PipelineModule


class PipelineEngine(DeepSpeedEngine):

    def __init__(self, args=None, model: Optional[PipelineModule] = None, optimizer=None,
                 model_parameters=None, training_data=None, lr_scheduler=None, mpu=None,
                 collate_fn=None, config=None, mesh_spec=None, seed: int = 42):
        if not (isinstance(model, PipelineModule)):
            raise AssertionError("PipelineEngine requires a PipelineModule")
        self.pipeline_module = model
        cfg = config if isinstance(config, DeepSpeedConfig) else DeepSpeedConfig(config)
        # Fold the module's stage count into the mesh (reference: topology implied by
        # PipelineModule + world size).
        if cfg.mesh.pipe in (1, None):
            cfg.mesh.pipe = model.num_stages
        if not (cfg.mesh.pipe == model.num_stages):
            raise AssertionError(f"config mesh.pipe={cfg.mesh.pipe} != PipelineModule.num_stages="
             f"{model.num_stages}")
        # In-stage tensor parallelism: when the mesh has a tensor axis AND the body
        # layer ships a manual-collective forward (tp_apply_factory — e.g. gpt2_pipe
        # blocks with split_qkv=True), the 1F1B shard_map goes manual over
        # {pipe, tensor} and body weights shard physically (Megatron col/row; the
        # reference's 3D topology, pipe/topology.py:243). Bodies without a tp
        # forward replicate over the tensor axis as before.
        from ...parallel.mesh import AXIS_SEQ, AXIS_TENSOR
        tp_axis = None
        body_layer = model._layers[model.body_start]
        if (getattr(cfg.mesh, "tensor", 1) or 1) > 1 \
                and getattr(body_layer, "tp_apply_factory", None) is not None:
            tp_axis = AXIS_TENSOR
        # pipe×seq: a seq axis + an sp-capable body runs the 1F1B body on
        # sequence-sharded chunks with ring attention (sp_apply_factory)
        sp_axis = None
        if (getattr(cfg.mesh, "seq", 1) or 1) > 1 \
                and getattr(body_layer, "sp_apply_factory", None) is not None:
            sp_axis = AXIS_SEQ
        model_obj = model.to_model(mesh_spec=None, name=f"pipe{model.num_stages}",
                                   tp_axis=tp_axis,
                                   tp_size=getattr(cfg.mesh, "tensor", None),
                                   ep_size=getattr(cfg.mesh, "expert", None),
                                   sp_axis=sp_axis)
        super().__init__(args=args, model=model_obj, optimizer=optimizer,
                         model_parameters=model_parameters, training_data=training_data,
                         lr_scheduler=lr_scheduler, mpu=mpu, collate_fn=collate_fn,
                         config=cfg, mesh_spec=mesh_spec, seed=seed)
        self.micro_batches = self.gradient_accumulation_steps()

    # The pipelined step consumes ALL microbatches in one loss evaluation (the fill/drain
    # loop), so the base engine's gas-scan is replaced by a single value_and_grad.
    def _build_train_step(self):
        def train_step(state: TrainState, batch, lr, pld_theta):
            rng = jax.random.fold_in(self._base_rng, state.global_step)
            loss, grads = self._loss_and_scaled_grads(
                state.params, state.scaler.cur_scale, batch, rng,
                step=state.global_step, pld_theta=pld_theta)
            grads = jax.lax.with_sharding_constraint(grads, self._grad_shardings)
            new_state, metrics = self._apply_update(state, grads, lr, 1)
            metrics["loss"] = loss
            return new_state, metrics

        jitted = jax.jit(train_step, donate_argnums=(0,),
                         out_shardings=(self._state_shardings, None))
        self._fns["train_step"] = jitted

    def train_batch(self, batch=None, data_iter=None):
        """One full batch = gas microbatches through the pipeline + optimizer step
        (reference ``pipe/engine.py:train_batch:295``)."""
        return super().train_batch(batch=batch, data_iter=data_iter)

    def eval_batch(self, batch, data_iter=None):
        """Pipelined forward-only evaluation (reference ``eval_batch:380``)."""
        if "pipe_eval" not in self._fns:
            def eval_step(params, batch):
                from ..utils import tree_cast
                # rng=None → deterministic pass (dropout off), reference eval semantics
                return self.module.loss_fn(tree_cast(params, self.compute_dtype),
                                           batch, None)
            self._fns["pipe_eval"] = jax.jit(eval_step)
        local = self._reshape_for_gas(batch)
        gbatch = self._globalize(local, leading_gas=True)
        return self._fns["pipe_eval"](self.state.params, gbatch)

    # Micro-step API is not meaningful when the pipeline consumes whole batches.
    def forward(self, *a, **kw):
        raise RuntimeError("PipelineEngine executes whole batches; use train_batch() / "
                           "eval_batch() (reference pipeline engines have the same contract)")

    __call__ = forward
    backward = forward
    step = forward

    def set_dataiterator(self, iterator):
        self._train_iter = iterator

    def is_first_stage(self) -> bool:
        return True  # SPMD: every process drives all stages

    def is_last_stage(self) -> bool:
        return True
