"""Host-driven pipeline executor: runs the instruction schedules for real.

Behavioural equivalent of reference ``deepspeed/runtime/pipe/engine.py:_exec_schedule:1360``
+ the ``_INSTRUCTION_MAP`` dispatch: interprets the per-stage instruction streams of
:mod:`.schedule` (``TrainSchedule``/``InferenceSchedule``) with per-stage jitted segment
functions, explicit activation/grad channels between adjacent stages, and a bounded
activation stash.

Role in the TPU design: the SPMD collective-permute loop (:meth:`PipelineModule.
make_1f1b_loss_fn`) is the compiled fast path, but it requires a homogeneous block body
(params stack over the ``pipe`` mesh axis). This executor lifts that restriction — stages
are arbitrary heterogeneous layer slices computed by ``partition_balanced`` over
``partition_method`` weights (reference ``module.py:_partition_layers:367``) — at the cost
of host-side dispatch per instruction. It also serves as the executable semantics of the
schedules: the tests drive it and check gradients against sequential autodiff and the
activation-stash bound against ``num_pipe_buffers()``.

Backward passes re-play the stage forward under ``jax.vjp`` from the stashed stage input
(per-microbatch remat), so stash entries are stage *inputs* only — at most
``num_pipe_buffers()`` live at once (asserted by tests), the 1F1B memory property.
"""

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .module import (LayerSpec, PipeLayer, TiedLayerSpec, _as_pipe_layer,
                     partition_balanced, partition_weights)
from .schedule import (BackwardPass, ForwardPass, InferenceSchedule, LoadMicroBatch,
                       OptimizerStep, PipeSchedule, RecvActivation, RecvGrad,
                       ReduceGrads, ReduceTiedGrads, SendActivation, SendGrad,
                       TrainSchedule)


class _NotReady(Exception):
    """A Recv whose matching Send happens later within the same global step (grad
    messages flow stage S-1→0 while stages are visited 0→S-1); the step loop defers
    the stage's remaining instructions and retries."""


class _ExecState:
    """Mutable execution state shared by the instruction handlers."""

    def __init__(self, n_stages: int, n_params: int):
        self.channels: Dict[Tuple, List] = {}          # (src,dst,kind,buf) -> FIFO
        self.stash = [dict() for _ in range(n_stages)]  # buf -> (mb_id, x)
        self.pending = [dict() for _ in range(n_stages)]  # buf/key -> payload
        self.fwd_count = [0] * n_stages
        self.bwd_count = [0] * n_stages
        self.grads: List[Any] = [None] * n_params
        self.losses: List[Any] = []
        self.outputs: Dict[int, Any] = {}
        self.peak_stash = 0

    def push(self, src, dst, kind, val):
        # FIFO per (src, dst, kind): P2P rendezvous matches by order, like the
        # reference's send/recv pairs — buffer ids are STAGE-LOCAL slot names (each
        # stage sizes its own ring via num_pipe_buffers) and never cross the wire.
        self.channels.setdefault((src, dst, kind), []).append(val)

    def pop(self, src, dst, kind):
        chan = self.channels.get((src, dst, kind))
        if not chan:
            raise _NotReady((src, dst, kind))
        return chan.pop(0)

    def note_peak(self):
        self.peak_stash = max(self.peak_stash, max(len(s) for s in self.stash))


class EagerPipelineExecutor:
    """Interpret pipeline schedules over heterogeneous layer stages."""

    def __init__(self, layers: Sequence, num_stages: int,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "parameters",
                 sample_input=None, seed: int = 0):
        self.num_stages = int(num_stages)
        self.loss_fn = loss_fn
        self._layers: List[PipeLayer] = [
            spec.build() if isinstance(spec, LayerSpec) else _as_pipe_layer(spec)
            for spec in layers]
        # tied groups (reference module.py:423-445): members share one parameter set;
        # init aliases them, ReduceTiedGrads sums their gradients (see train_batch_grads)
        self._tied_keys: List = [
            spec.key if isinstance(spec, TiedLayerSpec) else None for spec in layers]
        if not (sample_input is not None):
            raise AssertionError("sample_input required to trace layer shapes")

        # trace shapes + weights for partitioning
        rng = jax.random.PRNGKey(seed)
        x = sample_input
        self._abstract_params = []
        for layer in self._layers:
            p = jax.eval_shape(layer.init, rng, x)
            self._abstract_params.append(p)
            x = jax.eval_shape(layer.apply, p, x, None)

        weights = partition_weights(self._layers, self._abstract_params,
                                    partition_method)
        self.parts = partition_balanced(weights, self.num_stages)
        self._sample_input = sample_input
        self._stage_fwd_jit: Dict[int, Any] = {}
        self._stage_vjp_jit: Dict[Tuple, Any] = {}

    # ------------------------------------------------------------------ params
    def init_params(self, rng) -> List[Any]:
        """Per-layer parameter list (no stacking — stages may be heterogeneous)."""
        params = []
        tied_first: Dict[Any, int] = {}
        x = jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, l.dtype), _abs_tree(self._sample_input))
        for i, layer in enumerate(self._layers):
            key = self._tied_keys[i]
            if key is not None and key in tied_first:
                p = params[tied_first[key]]  # alias: tied members share parameters
            else:
                p = layer.init(jax.random.fold_in(rng, i), x)
                if key is not None:
                    tied_first[key] = i
            params.append(p)
            x_abs = jax.eval_shape(layer.apply, _abs_tree(p), _abs_tree(x), None)
            x = jax.tree_util.tree_map(lambda l: jnp.zeros(l.shape, l.dtype), x_abs)
        return params

    def _segment(self, s: int) -> Tuple[int, int]:
        return self.parts[s], self.parts[s + 1]

    def _stage_apply(self, s: int, seg_params, x, rng):
        lo, hi = self._segment(s)
        for i in range(lo, hi):
            lrng = None if rng is None else jax.random.fold_in(rng, i)
            x = self._layers[i].apply(seg_params[i - lo], x, lrng)
        return x

    def _fwd_fn(self, s: int):
        if s not in self._stage_fwd_jit:
            self._stage_fwd_jit[s] = jax.jit(
                lambda seg, x, r: self._stage_apply(s, seg, x, r))
        return self._stage_fwd_jit[s]

    def _bwd_fn(self, s: int, with_loss: bool):
        key = (s, with_loss)
        if key not in self._stage_vjp_jit:
            if with_loss:  # last stage: segment + loss, unit cotangent
                def f(seg, x, r, label, cot_unused):
                    def seg_loss(seg_, x_):
                        out = self._stage_apply(s, seg_, x_, r)
                        if self.loss_fn is not None:
                            return self.loss_fn(out, label)
                        return out if out.ndim == 0 else jnp.mean(out)
                    loss, vjp = jax.vjp(seg_loss, seg, x)
                    dseg, dx = vjp(jnp.float32(1.0))
                    return loss, dseg, dx
            else:
                def f(seg, x, r, label_unused, cot):
                    _, vjp = jax.vjp(
                        lambda seg_, x_: self._stage_apply(s, seg_, x_, r), seg, x)
                    dseg, dx = vjp(cot)
                    return jnp.float32(0.0), dseg, dx
            self._stage_vjp_jit[key] = jax.jit(f)
        return self._stage_vjp_jit[key]

    # ------------------------------------------------------------------ execution
    def train_batch_grads(self, params: List[Any], microbatches: List[Tuple],
                          rng=None):
        """Execute ``TrainSchedule`` for every stage; returns
        ``(mean_loss, per-layer grads, stats)``.

        ``microbatches``: list of ``(input, label)`` pairs. ``stats['peak_stash']`` is
        the max number of simultaneously-live stage-input stashes on any stage — the
        memory bound 1F1B promises.
        """
        M, S = len(microbatches), self.num_stages
        schedules: List[PipeSchedule] = [TrainSchedule(M, S, s) for s in range(S)]
        return self._execute(params, microbatches, schedules, rng, train=True)

    def infer_batch(self, params: List[Any], microbatches: List[Any], rng=None):
        """Execute ``InferenceSchedule``; returns the last stage's outputs per
        microbatch."""
        M, S = len(microbatches), self.num_stages
        schedules = [InferenceSchedule(M, S, s) for s in range(S)]
        mb = [(m, None) for m in microbatches]
        _, _, stats = self._execute(params, mb, schedules, rng, train=False)
        return stats["outputs"]

    def _execute(self, params, microbatches, schedules, rng, train: bool):
        S = self.num_stages
        seg_params = [params[self._segment(s)[0]:self._segment(s)[1]]
                      for s in range(S)]
        st = _ExecState(S, len(params))

        # Dataflow execution: each stage consumes ITS OWN instruction stream strictly in
        # order (that order is what encodes 1F1B pacing and the stash bound); cross-stage
        # synchronisation comes from the channels — a Recv with no matching Send yet
        # blocks that stage until another stage produces it. This matches the reference
        # executor, where stages are independent processes and P2P ops rendezvous.
        queues: List[List] = [[c for step in sched for c in step]
                              for sched in schedules]
        ptr = [0] * S
        while any(ptr[s] < len(queues[s]) for s in range(S)):
            progressed = False
            for s in range(S):
                while ptr[s] < len(queues[s]):
                    try:
                        self._dispatch(s, queues[s][ptr[s]], st, seg_params,
                                       microbatches, rng, train)
                    except _NotReady:
                        break
                    ptr[s] += 1
                    progressed = True
                    st.note_peak()
            if not (progressed):
                raise AssertionError("schedule deadlock: " +
                str([(s, queues[s][ptr[s]]) for s in range(S)
                     if ptr[s] < len(queues[s])]))

        stats = {"peak_stash": st.peak_stash,
                 "outputs": [st.outputs[m] for m in sorted(st.outputs)]}
        if not train:
            return None, None, stats
        M = len(microbatches)
        if not (all(f == M for f in st.fwd_count)):
            raise AssertionError(st.fwd_count)
        if not (all(b == M for b in st.bwd_count)):
            raise AssertionError(st.bwd_count)
        mean_loss = jnp.mean(jnp.stack(st.losses))
        inv_m = 1.0 / M
        grads = [jax.tree_util.tree_map(lambda g: g * inv_m, g) if g is not None else g
                 for g in st.grads]
        # ReduceTiedGrads: every tied member gets the group's summed gradient, so
        # identical (aliased) parameters stay identical under any per-layer update
        groups: Dict[Any, List[int]] = {}
        for i, key in enumerate(self._tied_keys):
            if key is not None:
                groups.setdefault(key, []).append(i)
        for members in groups.values():
            total = grads[members[0]]
            for i in members[1:]:
                total = jax.tree_util.tree_map(jnp.add, total, grads[i])
            for i in members:
                grads[i] = total
        return mean_loss, grads, stats

    # ------------------------------------------------------------------ dispatch
    def _dispatch(self, s: int, cmd, st: _ExecState, seg_params, microbatches,
                  rng, train: bool):
        S = self.num_stages

        def srng(mb_id):
            return (None if rng is None else
                    jax.random.fold_in(jax.random.fold_in(rng, mb_id), s))

        if isinstance(cmd, LoadMicroBatch):
            mb_id = st.fwd_count[s]
            x, _ = microbatches[mb_id]
            st.stash[s][cmd.buffer_id] = (mb_id, x)
        elif isinstance(cmd, RecvActivation):
            st.stash[s][cmd.buffer_id] = st.pop(s - 1, s, "act")
        elif isinstance(cmd, ForwardPass):
            mb_id, x = st.stash[s][cmd.buffer_id]
            y = self._fwd_fn(s)(seg_params[s], x, srng(mb_id))
            st.fwd_count[s] += 1
            if s == S - 1:
                st.outputs[mb_id] = y
            else:
                st.pending[s][cmd.buffer_id] = (mb_id, y)
            if not train:  # inference never backwards: free the input now
                st.stash[s].pop(cmd.buffer_id, None)
        elif isinstance(cmd, SendActivation):
            st.push(s, s + 1, "act", st.pending[s].pop(cmd.buffer_id))
        elif isinstance(cmd, RecvGrad):
            st.pending[s][("cot", cmd.buffer_id)] = st.pop(s + 1, s, "grad")
        elif isinstance(cmd, BackwardPass):
            mb_id, x = st.stash[s].pop(cmd.buffer_id)
            if s == S - 1:
                label = microbatches[mb_id][1]
                loss, dseg, dx = self._bwd_fn(s, True)(
                    seg_params[s], x, srng(mb_id), label, None)
                st.losses.append(loss)
            else:
                mb_chk, cot = st.pending[s].pop(("cot", cmd.buffer_id))
                if not (mb_chk == mb_id):
                    raise AssertionError(f"grad/act microbatch mismatch: {mb_chk} vs {mb_id}")
                _, dseg, dx = self._bwd_fn(s, False)(
                    seg_params[s], x, srng(mb_id), None, cot)
            lo, _ = self._segment(s)
            for k, d in enumerate(dseg):
                i = lo + k
                st.grads[i] = d if st.grads[i] is None else \
                    jax.tree_util.tree_map(jnp.add, st.grads[i], d)
            st.bwd_count[s] += 1
            if s > 0:
                st.pending[s][("grad", cmd.buffer_id)] = (mb_id, dx)
        elif isinstance(cmd, SendGrad):
            st.push(s, s - 1, "grad", st.pending[s].pop(("grad", cmd.buffer_id)))
        elif isinstance(cmd, (ReduceGrads, ReduceTiedGrads, OptimizerStep)):
            pass  # single-process: reductions are identity; the step is the caller's
        else:
            raise TypeError(f"unknown instruction {cmd!r}")


def _abs_tree(p):
    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(np.shape(l), l.dtype), p)
