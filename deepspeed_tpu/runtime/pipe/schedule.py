"""Pipeline instruction schedules — pure logic, no devices.

Behavioural equivalent of reference ``deepspeed/runtime/pipe/schedule.py`` (``TrainSchedule:184``,
``InferenceSchedule:131``, ``DataParallelSchedule:299``, instruction classes ``PipeInstruction:324``).

On TPU the *executed* pipeline is an SPMD collective-permute loop compiled by XLA
(``runtime/pipe/engine.py``) — every stage runs the same program and the "instructions" are
iterations of a ``lax.scan``. These instruction streams remain first-class because they (a) define
the semantics the SPMD loop must match (each microbatch forwarded and backwarded exactly once per
stage, in dataflow order), (b) drive the host-side eager executor used for debugging, and (c) are
pure-python testable without any mesh, exactly like the reference's schedule tests
(``tests/unit/runtime/pipe/test_pipe_schedule.py``).

The generators here are written from the 1F1B algorithm (one-forward-one-backward: each stage
runs ``stages - stage_id - 1`` warmup forwards, then alternates fwd/bwd, then drains), not
transcribed from the reference.
"""

from typing import Iterable, List


# --------------------------------------------------------------------------- instructions
class PipeInstruction:
    """A single step in a pipeline schedule (reference ``schedule.py:324``)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        if self.kwargs:
            args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
            return f"{self.name}({args})"
        return self.name

    def __eq__(self, other):
        return (type(self) is type(other)) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((self.name, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Take the optimizer step (all stages, end of batch)."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction (psum over the data axis in SPMD)."""


class ReduceTiedGrads(PipeInstruction):
    """All-reduce gradients of tied weights across the stages that own them."""


class BufferOpInstruction(PipeInstruction):
    """Instruction operating on an activation buffer slot ``buffer_id``."""

    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """First/last stage: load microbatch into the buffer."""


class ForwardPass(BufferOpInstruction):
    """Run the stage's layers forward on the buffer."""


class BackwardPass(BufferOpInstruction):
    """Backprop the stage's layers for the buffer's microbatch."""


class SendActivation(BufferOpInstruction):
    """Send the buffer's activation to the next stage."""


class RecvActivation(BufferOpInstruction):
    """Receive an activation from the previous stage into the buffer."""


class SendGrad(BufferOpInstruction):
    """Send the activation-gradient to the previous stage."""


class RecvGrad(BufferOpInstruction):
    """Receive the activation-gradient from the next stage."""


# --------------------------------------------------------------------------- schedules
class PipeSchedule:
    """Base: yields lists of :class:`PipeInstruction` per step for one stage.

    Mirrors the reference contract (``schedule.py:PipeSchedule``): ``steps()`` generates the
    per-step instruction lists; iteration yields them in order.
    """

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        if not (0 <= stage_id < stages):
            raise AssertionError('0 <= stage_id < stages')
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterable[List[PipeInstruction]]:
        raise NotImplementedError

    @property
    def num_micro_batches(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self) -> int:
        """Activation buffer slots needed (1F1B in-flight bound)."""
        return self.stages

    def _buffer_idx(self, micro_batch_id: int) -> int:
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only pipeline: fill-and-drain (reference ``schedule.py:131``)."""

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for step_id in range(total):
            cmds: List[PipeInstruction] = []
            mb = step_id - self.stage_id
            if not (0 <= mb < self.micro_batches):
                yield cmds
                continue
            buf = self._buffer_idx(mb)
            if self.is_first_stage:
                cmds.append(LoadMicroBatch(buf))
            else:
                cmds.append(RecvActivation(buf))
            cmds.append(ForwardPass(buf))
            if not self.is_last_stage:
                cmds.append(SendActivation(buf))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B: warmup forwards, steady-state alternation, drain backwards, then reduce+step.

    Invariants (tested in ``tests/unit/runtime/pipe/test_pipe_schedule.py``): every microbatch is
    forwarded then backwarded exactly once per stage; a stage never has more than
    ``stages - stage_id`` microbatches in flight; sends/recvs pair up across adjacent stages.
    """

    def num_pipe_buffers(self) -> int:
        # 1F1B keeps at most (stages - stage_id) microbatches in flight on this stage.
        return max(2, min(self.stages - self.stage_id, self.micro_batches))

    def _fwd_cmds(self, micro_batch_id: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(micro_batch_id)
        cmds: List[PipeInstruction] = [
            LoadMicroBatch(buf) if self.is_first_stage else RecvActivation(buf),
            ForwardPass(buf),
        ]
        if not self.is_last_stage:
            cmds.append(SendActivation(buf))
        return cmds

    def _bwd_cmds(self, micro_batch_id: int) -> List[PipeInstruction]:
        buf = self._buffer_idx(micro_batch_id)
        cmds: List[PipeInstruction] = []
        if not self.is_last_stage:
            cmds.append(RecvGrad(buf))
        cmds.append(BackwardPass(buf))
        if not self.is_first_stage:
            cmds.append(SendGrad(buf))
        return cmds

    def steps(self):
        M, S, s = self.micro_batches, self.stages, self.stage_id
        warmup = min(S - s - 1, M)
        fwd_done = 0
        bwd_done = 0
        # Each stage starts its local step stream offset by its depth so that cross-stage
        # send/recv pairs align step-for-step when all streams are laid side by side.
        for _ in range(s):
            yield []  # idle while the wavefront reaches this stage

        for _ in range(warmup):  # fill: forwards only
            yield self._fwd_cmds(fwd_done)
            fwd_done += 1

        while fwd_done < M:  # steady state: one forward, one backward per round
            yield self._fwd_cmds(fwd_done)
            fwd_done += 1
            yield self._bwd_cmds(bwd_done)
            bwd_done += 1

        while bwd_done < M:  # drain: remaining backwards
            yield self._bwd_cmds(bwd_done)
            bwd_done += 1

        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule: plain gradient accumulation
    (reference ``schedule.py:299``)."""

    def num_pipe_buffers(self) -> int:
        return 1

    def steps(self):
        for mb in range(self.micro_batches):
            yield [LoadMicroBatch(0), ForwardPass(0), BackwardPass(0)]
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]
