from .module import (FlaxPipeLayer, LambdaLayer, LayerSpec, PipeLayer, PipelineModule,
                     TiedLayerSpec, partition_balanced)
from .schedule import (BackwardPass, DataParallelSchedule, ForwardPass, InferenceSchedule,
                       LoadMicroBatch, OptimizerStep, PipeInstruction, PipeSchedule,
                       RecvActivation, RecvGrad, ReduceGrads, ReduceTiedGrads,
                       SendActivation, SendGrad, TrainSchedule)
