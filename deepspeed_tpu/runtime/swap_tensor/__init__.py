"""NVMe swap tier (reference deepspeed/runtime/swap_tensor): see zero/offload.py _NVMeMomentStore + ops/aio."""
from ..zero.offload import _NVMeMomentStore as NVMeMomentStore  # noqa: F401
