"""Config-driven activation checkpointing (recompute).

Reference: ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
(``CheckpointFunction:499``, ``checkpoint():749``, ``configure():831``). The reference
re-implements autograd checkpointing with partitioned/offloaded activation storage and RNG
state tracking; on TPU every mechanism collapses into ``jax.checkpoint``:

- recompute-in-backward → ``jax.checkpoint`` (XLA schedules the recompute);
- ``partition_activations`` (shard saved activations across TP ranks) → saved residuals
  are sharded arrays already under ``pjit`` — a sharding constraint on the wrapped fn's
  output is the whole mechanism;
- CPU checkpointing (offload saved activations to host) → ``jax.checkpoint`` policies
  with ``offload_to_host`` (``save_and_offload_only_these_names``) where supported —
  approximated here by the ``offload`` policy alias;
- ``CudaRNGStatesTracker`` → unnecessary: jax PRNG keys are values, so recompute is
  deterministic by construction.

``configure()`` + ``checkpoint()`` keep the reference's module-level API so model code
ports over unchanged.
"""

from typing import Any, Callable, Optional

import jax

from ...utils.logging import logger

_config = None

# name → jax.checkpoint policy (None = save nothing, i.e. full recompute)
POLICIES = {
    "nothing_saveable": None,
    "full": None,
    "dots": "dots_with_no_batch_dims_saveable",
    "dots_saveable": "dots_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
    "checkpoint_dots": "dots_saveable",
    "everything_saveable": "everything_saveable",
    # activation CPU offload (reference checkpoint_in_cpu / cpu_checkpointing):
    # matmul outputs are SAVED but live in pinned host memory, streamed back for
    # the backward — HBM cost of full remat, compute cost of dots-saveable
    "offload_dots": ("offload_dot_with_no_batch_dims", "device", "pinned_host"),
}


def _resolve_policy(name: str):
    if name not in POLICIES:
        raise ValueError(f"unknown activation-checkpointing policy {name!r}; "
                         f"known: {sorted(POLICIES)}")
    attr = POLICIES[name]
    if attr is None:
        return None
    if isinstance(attr, tuple):
        factory, *args = attr
        return getattr(jax.checkpoint_policies, factory)(*args)
    return getattr(jax.checkpoint_policies, attr)


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference ``configure():831`` — store the active config for ``checkpoint()``."""
    global _config
    if deepspeed_config is not None:
        cfg = getattr(deepspeed_config, "activation_checkpointing", deepspeed_config)
    else:
        from ...config.config import ActivationCheckpointingConfig
        cfg = ActivationCheckpointingConfig()
    if partition_activations is not None:
        cfg.partition_activations = partition_activations
    if checkpoint_in_cpu is not None:
        cfg.cpu_checkpointing = checkpoint_in_cpu
    _config = cfg
    logger.info(f"activation checkpointing configured: policy={cfg.policy} "
                f"partition_activations={cfg.partition_activations}")
    return _config


def is_configured() -> bool:
    return _config is not None


def _active_policy_name(policy: Optional[str]) -> str:
    if policy is not None:
        return policy
    if _config is not None:
        # checkpoint_in_cpu / cpu_checkpointing promotes the policy to host offload
        if getattr(_config, "cpu_checkpointing", False):
            return "offload_dots"
        return _config.policy
    return "nothing_saveable"


def checkpoint(function: Callable, *args, policy: Optional[str] = None) -> Any:
    """Recompute ``function``'s activations in the backward pass
    (reference ``checkpoint():749``). Usable before ``configure()`` — defaults to full
    recompute, like the reference's default config."""
    pol = _resolve_policy(_active_policy_name(policy))
    wrapped = jax.checkpoint(function, policy=pol, prevent_cse=False)
    return wrapped(*args)


def checkpoint_wrapper(function: Callable, policy: Optional[str] = None) -> Callable:
    """Decorator form: returns a rematerialising version of ``function``."""
    pol = _resolve_policy(_active_policy_name(policy))
    return jax.checkpoint(function, policy=pol, prevent_cse=False)


def reset():
    """Reference ``reset()``: clear buffered state between iterations. Also clears the
    module-global config so ``checkpoint()`` returns to the unconfigured default —
    nothing else is buffered host-side on TPU."""
    global _config
    _config = None


def model_parallel_cuda_manual_seed(seed: int):
    """Reference RNG-tracker API — jax PRNG keys make it unnecessary; kept for source
    compatibility (no-op)."""
