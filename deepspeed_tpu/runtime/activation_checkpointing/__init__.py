from . import checkpointing
