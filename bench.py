"""Benchmark entry point — prints ONE JSON line.

Runs the BASELINE config-1 workload shape on whatever chip is attached: GPT-2 125M causal-LM
training, ZeRO stage 1, bf16, fused train step. Metric: training throughput in tokens/sec/chip.
``vs_baseline`` is 1.0-relative once a reference number exists; ``BASELINE.json`` ``published``
is empty for TPU configs, so we report the ratio against the first recorded value of this same
bench (stored in ``.bench_baseline.json`` on first successful run).
"""

import json
import os
import sys
import time


def main():
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2Config, gpt2_model

    import jax

    seq = int(os.environ.get("BENCH_SEQ", 1024))
    micro = int(os.environ.get("BENCH_MICRO", 8))
    steps = int(os.environ.get("BENCH_STEPS", 20))
    warmup = 3

    n_chips = jax.device_count()
    cfg = GPT2Config(vocab_size=50304,  # padded to 128 multiple for MXU tiling
                     n_positions=seq, n_embd=768, n_layer=12, n_head=12,
                     dropout=0.0, remat=True, scan_layers=True)
    model = gpt2_model(cfg, sample_seq_len=seq)
    config = {
        "train_batch_size": micro * n_chips,
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 50304, size=(micro * n_chips, seq),
                                       dtype=np.int32)}
    for _ in range(warmup):
        engine.train_batch(batch)
    jax.effects_barrier()
    t0 = time.perf_counter()
    for _ in range(steps):
        engine.train_batch(batch)
    jax.effects_barrier()
    dt = time.perf_counter() - t0

    tokens_per_sec_per_chip = micro * n_chips * seq * steps / dt / n_chips
    baseline_file = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".bench_baseline.json")
    vs_baseline = 1.0
    try:
        if os.path.exists(baseline_file):
            with open(baseline_file) as f:
                vs_baseline = tokens_per_sec_per_chip / json.load(f)["value"]
        else:
            with open(baseline_file, "w") as f:
                json.dump({"value": tokens_per_sec_per_chip}, f)
    except Exception:
        pass

    print(json.dumps({
        "metric": "gpt2_125m_zero1_bf16_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
