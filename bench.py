"""Benchmark entry point — prints ONE JSON line.

Runs the BASELINE config-1 workload shape on whatever chip is attached: GPT-2 125M causal-LM
training, ZeRO stage 1, bf16, fused train step (flash-attention Pallas kernel on TPU).
Metric: training throughput in tokens/sec/chip, plus honest ``tflops_per_chip`` (model FLOPs,
not recompute) and ``mfu`` against the chip's peak bf16 rate. ``vs_baseline`` is the ratio
against the first recorded value of this bench (``.bench_baseline.json``).

``--mode inference`` benches the serving path: p50 TTFT (prefill) + decode tokens/sec on the
flagship model — the second BASELINE north-star (config 5 shape, scaled to one chip).
"""

import argparse
import json
import os
import sys
import time

# Peak dense bf16 TFLOP/s per chip by device_kind (public spec sheets).
PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def _sync(x):
    """Device sync by host-fetching a (scalar) result — jax.effects_barrier does not reliably
    block on tunneled platforms."""
    import numpy as np
    return np.asarray(x)


def peak_tflops():
    import jax
    kind = jax.devices()[0].device_kind
    for k, v in PEAK_TFLOPS.items():
        if kind.startswith(k):
            return v
    return None


def kernel_gate(mode: str):
    """Compiled-kernel pre-bench check (VERDICT r3 next #8): verify the Mosaic
    kernels the selected bench relies on against their XLA references ON THE REAL
    CHIP before any number is recorded, so a kernel regression fails the bench
    loudly instead of silently benching a fallback. Checks and tolerances live in
    ``deepspeed_tpu.ops.kernel_checks`` — the SAME source the TPU test lane runs,
    so the two cannot drift. Returns the per-kernel max-abs-err dict; raises on
    any failure. No-op (returns None) off-TPU."""
    import jax

    if jax.default_backend() != "tpu":
        return None
    from deepspeed_tpu.ops.kernel_checks import run_kernel_checks
    names = {"train": ("flash_fwd", "flash_bwd", "block_sparse"),
             "inference": ("flash_fwd", "flash_alibi", "decode")}[mode]
    return run_kernel_checks(names)


def bench_train():
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2Config, gpt2_model

    import jax

    seq = int(os.environ.get("BENCH_SEQ", 1024))
    micro = int(os.environ.get("BENCH_MICRO", 24))
    steps = int(os.environ.get("BENCH_STEPS", 20))
    warmup = 3

    n_chips = jax.device_count()
    # micro=24 + dots remat (save matmul outputs, recompute elementwise) + length-
    # dispatched attention measured fastest on v5e: 67.8k tok/s vs 62.5k for the
    # round-1 micro=32 full-remat flash config
    # BENCH_VOCAB_CHUNK>0 switches the loss to the chunked-vocab CE (no (b, t, V)
    # logits buffer) — required for the long-sequence shapes (seq 32k+)
    cfg = GPT2Config(vocab_size=50304,  # padded to 128 multiple for MXU tiling
                     n_positions=seq, n_embd=768, n_layer=12, n_head=12,
                     dropout=0.0, remat=True,
                     # "dots" (save matmul outputs) is fastest at the canonical
                     # shape; extreme sequence lengths need "full" remat
                     remat_policy=os.environ.get("BENCH_REMAT_POLICY", "dots"),
                     scan_layers=True,
                     vocab_chunk=int(os.environ.get("BENCH_VOCAB_CHUNK", 0)))
    model = gpt2_model(cfg, sample_seq_len=seq)
    config = {
        "train_batch_size": micro * n_chips,
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 2e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 50304, size=(micro * n_chips, seq),
                                       dtype=np.int32)}
    for _ in range(warmup):
        loss = engine.train_batch(batch)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    _sync(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec_per_chip = micro * n_chips * seq * steps / dt / n_chips
    flops_per_token = cfg.flops_per_token()          # 6N + attention (model FLOPs, no remat)
    tflops_per_chip = tokens_per_sec_per_chip * flops_per_token / 1e12
    peak = peak_tflops()

    baseline_file = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".bench_baseline.json")
    vs_baseline = 1.0
    baseline = None
    try:
        if os.path.exists(baseline_file):
            with open(baseline_file) as f:
                baseline = json.load(f)
            vs_baseline = tokens_per_sec_per_chip / baseline["value"]
        else:
            baseline = {"value": tokens_per_sec_per_chip, "micro_batch": micro, "seq": seq}
            with open(baseline_file, "w") as f:
                json.dump(baseline, f)
    except Exception:
        pass

    out = {
        "metric": "gpt2_125m_zero1_bf16_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_per_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
        "tflops_per_chip": round(tflops_per_chip, 2),
        "micro_batch": micro,
        "seq": seq,
    }
    # Surface the baseline's workload shape when known-different, so the ratio is readable
    # as "same model/task, tuned config" rather than silently apples-to-oranges.
    if baseline and baseline.get("micro_batch", micro) != micro:
        out["baseline_micro_batch"] = baseline["micro_batch"]
    if baseline and baseline.get("seq", seq) != seq:
        out["baseline_seq"] = baseline["seq"]
    if peak:
        out["mfu"] = round(tflops_per_chip / peak, 4)
    print(json.dumps(_with_gate(out)))


def bench_inference():
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import gpt2_cfg

    prompt_len = int(os.environ.get("BENCH_PROMPT", 512))
    # long enough that the differencing signal (dt_long - dt_short ≈ 550ms at 125M)
    # dwarfs tunnel-RTT jitter (each generate() pays two host syncs, σ ≈ 40ms/diff)
    gen_len = int(os.environ.get("BENCH_GEN", 1536))
    batch = int(os.environ.get("BENCH_INFER_BATCH", 1))
    iters = int(os.environ.get("BENCH_INFER_ITERS", 13))

    # BENCH_MOE_EXPERTS>0 benches the MoE serving path (every 2nd layer's FFN is
    # a gated expert mixture — reference moe_inference.py)
    n_experts = int(os.environ.get("BENCH_MOE_EXPERTS", 0))
    cfg = gpt2_cfg(vocab_size=50304, max_seq_len=prompt_len + gen_len,
                   n_embd=768, n_layer=12, n_head=12, num_experts=n_experts)
    engine = ds.init_inference(model=cfg, config={"dtype": "bfloat16",
                                                  "max_out_tokens": prompt_len + gen_len})

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 50304, size=(batch, prompt_len), dtype=np.int32)

    # warmup (compiles prefill + decode)
    out = engine.generate(ids, max_new_tokens=8)
    _sync(out)

    # Dispatch+sync round-trip floor: on a tunneled platform (axon) every host sync
    # pays a network RTT (~90-130ms, jittery) that would otherwise be booked as
    # TTFT/decode time. Decode tok/s is measured by DIFFERENCING two generation
    # lengths — (T_long - T_short) / (len_long - len_short) — which cancels every
    # constant overhead (RTT, prefill, dispatch) exactly; TTFT is reported RTT-
    # corrected with the measured floor.
    import jax.numpy as jnp_
    import jax as jax_
    trivial = jax_.jit(lambda x: x + 1)
    _sync(trivial(jnp_.ones(8)))
    rtts = []
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(trivial(jnp_.ones(8)))
        rtts.append(time.perf_counter() - t0)
    rtt = sorted(rtts)[1]
    assert gen_len >= 16, f"BENCH_GEN must be >= 16 for differencing (got {gen_len})"
    short_len = max(8, gen_len // 4)
    # compile BOTH loop lengths so no timed sample pays XLA compilation
    _sync(engine.generate(ids, max_new_tokens=short_len))
    _sync(engine.generate(ids, max_new_tokens=gen_len))

    def timed(n_tokens):
        t0 = time.perf_counter()
        out = engine.generate(ids, max_new_tokens=n_tokens)
        _sync(out)
        return time.perf_counter() - t0

    ttfts, decode_tps = [], []
    for _ in range(iters):
        dt_long = timed(gen_len)
        ttfts.append(max(engine.ttft - rtt, 1e-9))
        dt_short = timed(short_len)
        per_token = max(dt_long - dt_short, 1e-9) / (gen_len - short_len)
        decode_tps.append(batch / per_token)

    ttft_p50 = sorted(ttfts)[len(ttfts) // 2] * 1e3 if ttfts else None
    tps = sorted(decode_tps)[len(decode_tps) // 2]
    out = {
        "metric": ("gpt2_125m_moe_bf16_decode_tokens_per_sec" if n_experts
                   else "gpt2_125m_bf16_decode_tokens_per_sec"),
        "value": round(tps, 2),
        "unit": "tokens/s",
        "vs_baseline": 1.0,
        "dispatch_rtt_ms": round(rtt * 1e3, 2),
    }
    if n_experts:
        out["num_experts"] = n_experts
    if ttft_p50 is not None:
        out["ttft_p50_ms"] = round(ttft_p50, 2)
    print(json.dumps(_with_gate(out)))


def bench_train_13b():
    """North-star config 3 (BASELINE.json): GPT-2 1.3B, ZeRO-3 param partitioning —
    scaled to one chip via the host optimizer-offload tier (fp32 masters + moments in
    host RAM; HBM holds bf16 params + grads, which is the only way 1.3B trains on a
    16 GB chip without a pod).

    Honesty note: on the tunneled bench host, host↔device bandwidth is ~24 MB/s H2D /
    ~8 MB/s D2H (vs ~16-32 GB/s PCIe on real metal), so wall-clock throughput is
    tunnel-IO-bound. The artifact therefore reports BOTH the measured wall-clock
    tokens/s and the device-compute-only tokens/s (the jitted fwd+bwd step, which is
    what a real deployment approaches as the host link speeds up), plus the measured
    link bandwidths so future rounds are comparable.
    """
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import GPT2Config, gpt2_model

    import jax

    seq = int(os.environ.get("BENCH_SEQ", 1024))
    micro = int(os.environ.get("BENCH_13B_MICRO", 4))
    steps = int(os.environ.get("BENCH_13B_STEPS", 2))

    cfg = GPT2Config(vocab_size=50304, n_positions=seq, n_embd=2048, n_layer=24,
                     n_head=16, dropout=0.0, remat=True, remat_policy="dots",
                     scan_layers=True)
    model = gpt2_model(cfg, sample_seq_len=seq)
    config = {
        "train_batch_size": micro,
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3,
                              "offload_optimizer": {"device": "cpu"}},
        "gradient_clipping": 1.0,
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(engine.state.params))

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 50304, size=(micro, seq), dtype=np.int32)}
    loss = engine.train_batch(batch)      # compile + first host step
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    _sync(loss)
    dt = (time.perf_counter() - t0) / steps
    wall_tps = micro * seq / dt

    # device-compute-only: repeated dispatch of the jitted grad step (no host Adam /
    # transfers in the timed region); N-chain differencing cancels dispatch+fetch RTT
    jitted = engine._fns["train_step"]
    gbatch = engine._globalize(engine._reshape_for_gas(batch), leading_gas=True)
    theta = np.float32(1.0)

    def run_n(n):
        t0 = time.perf_counter()
        for _ in range(n):
            st, grads, _m = jitted(engine.state, gbatch, theta)
            engine.state = st
        _sync(_m["loss"])
        return time.perf_counter() - t0

    run_n(1)
    t2, t6 = run_n(2), run_n(6)
    dev_dt = max((t6 - t2) / 4, 1e-9)
    dev_tps = micro * seq / dev_dt

    flops_per_token = cfg.flops_per_token()
    peak = peak_tflops()
    out = {
        "metric": "gpt2_1.3b_zero3_offload_train_tokens_per_sec_per_chip",
        "value": round(wall_tps, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": 1.0,
        "params": n_params,
        "tunnel_io_bound": True,
        "device_compute_tokens_per_sec": round(dev_tps, 2),
        "device_compute_tflops_per_chip": round(dev_tps * flops_per_token / 1e12, 2),
        "micro_batch": micro,
        "seq": seq,
    }
    if peak:
        out["device_compute_mfu"] = round(dev_tps * flops_per_token / 1e12 / peak, 4)
    print(json.dumps(_with_gate(out)))


def bench_inference_7b():
    """North-star config 5 (BASELINE.json): BLOOM-7B serving TTFT — scaled to one
    chip (reference runs TP over v4-16; one v5e chip holds the 7.1B bf16 weights).
    Weights are randomly initialised ON DEVICE (no 14 GB tunnel transfer; TTFT does
    not depend on weight values)."""
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import bloom_cfg

    prompt_len = int(os.environ.get("BENCH_PROMPT", 512))
    iters = int(os.environ.get("BENCH_7B_ITERS", 3))
    # batched serving throughput (reference inference story is per-GPU THROUGHPUT,
    # engine.py:541 forward batching): decode_tokens_per_sec is the batch aggregate
    batch = int(os.environ.get("BENCH_7B_BATCH", 1))

    # BLOOM-7B1 shape: 30 layers, hidden 4096, 32 heads, alibi, vocab 250880
    cfg = bloom_cfg(vocab_size=250880, max_seq_len=prompt_len + 64,
                    n_embd=4096, n_layer=30, n_head=32)
    engine = ds.init_inference(model=cfg, config={"dtype": "bfloat16",
                                                  "max_out_tokens": prompt_len + 64})

    import jax
    import jax.numpy as jnp_
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(batch, prompt_len), dtype=np.int32)

    trivial = jax.jit(lambda x: x + 1)
    _sync(trivial(jnp_.ones(8)))
    rtts = []
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(trivial(jnp_.ones(8)))
        rtts.append(time.perf_counter() - t0)
    rtt = sorted(rtts)[1]

    _sync(engine.generate(ids, max_new_tokens=4))    # compile prefill+decode
    ttfts = []
    for _ in range(iters):
        _sync(engine.generate(ids, max_new_tokens=4))
        ttfts.append(max(engine.ttft - rtt, 1e-9))
    ttft_p50 = sorted(ttfts)[len(ttfts) // 2] * 1e3

    # Pure prefill execution time by k-differencing (cancels dispatch/RTT exactly):
    # k sequential prefill dispatches, fetch the last token — (T_k2 - T_k1)/(k2 - k1).
    from deepspeed_tpu.models.causal_lm import init_cache
    prefill, _ = engine._loop_fns(False, 1.0, 0, 1.0, prompt_len + 64)
    caches = init_cache(engine.model_config, batch, prompt_len + 64,
                        dtype=engine.dtype)
    lens0 = jnp_.full((batch,), prompt_len, jnp_.int32)
    ids_dev = jnp_.asarray(ids)
    key = jax.random.PRNGKey(0)

    def prefill_k(k):
        t0 = time.perf_counter()
        for _ in range(k):
            tok0, _, _ = prefill(engine.params, ids_dev, caches, lens0, key)
        _sync(tok0)
        return time.perf_counter() - t0

    prefill_k(1)
    exec_ms = []
    for _ in range(iters):
        t1, t9 = prefill_k(1), prefill_k(9)
        exec_ms.append((t9 - t1) / 8 * 1e3)
    prefill_exec_p50 = sorted(exec_ms)[len(exec_ms) // 2]

    # Steady-state decode tokens/s by generation-length differencing (same
    # methodology as bench_inference): cancels prefill + all constant overhead.
    short_len, long_len = 16, 64
    _sync(engine.generate(ids, max_new_tokens=short_len))
    _sync(engine.generate(ids, max_new_tokens=long_len))
    decode_tps = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(engine.generate(ids, max_new_tokens=long_len))
        dt_long = time.perf_counter() - t0
        t0 = time.perf_counter()
        _sync(engine.generate(ids, max_new_tokens=short_len))
        dt_short = time.perf_counter() - t0
        per_token = max(dt_long - dt_short, 1e-9) / (long_len - short_len)
        decode_tps.append(batch / per_token)
    decode_p50 = sorted(decode_tps)[len(decode_tps) // 2]

    # Executed prefill matmul FLOPs: the head (tied wte, v*d params) runs at ONE
    # position (logits_positions), not all prompt_len — billing it per-position
    # would overstate MFU by ~1.14x at BLOOM's 250k vocab.
    vd = cfg.vocab_size * cfg.n_embd
    flops_prefill = 2.0 * ((cfg.num_params() - vd) * prompt_len + vd) * batch
    prefill_tflops = flops_prefill / (prefill_exec_p50 / 1e3) / 1e12
    peak = peak_tflops()
    # Headline keeps the round-3 methodology (single-shot TTFT minus one measured
    # dispatch RTT) for longitudinal comparability; prefill_exec_p50_ms is the
    # k-differenced on-device execution time (cancels dispatch/RTT exactly — the
    # TTFT a production deployment observes, and the basis for prefill_mfu). On the
    # tunnel the corrected single-shot's residual is RTT jitter (~±15 ms) and can
    # even undershoot the differenced figure.
    out = {
        "metric": "bloom_7b_bf16_prefill_ttft_p50_ms",
        "value": round(ttft_p50, 2),
        "unit": "ms",
        "vs_baseline": 1.0,
        "params": cfg.num_params(),
        "prompt_len": prompt_len,
        "dispatch_rtt_ms": round(rtt * 1e3, 2),
        "prefill_exec_p50_ms": round(prefill_exec_p50, 2),
        "prefill_tflops": round(prefill_tflops, 1),
        "decode_tokens_per_sec": round(decode_p50, 2),
        "batch": batch,
    }
    if peak:
        out["prefill_mfu"] = round(prefill_tflops / peak, 4)
    print(json.dumps(_with_gate(out)))


def _respawn_virtual_cpu(flag_env: str, lane_flag: str, smoke: bool,
                         out_path) -> int:
    """Re-exec this bench lane in a child pinned to the virtual 8-device CPU
    mesh (shared dead-tunnel scaffold of ``--overlap`` and ``--wq``; the
    caller decides WHEN — the lanes have different device requirements)."""
    import subprocess
    from deepspeed_tpu.utils.device_probe import virtual_cpu_mesh_env
    env = virtual_cpu_mesh_env(8)
    env[flag_env] = "1"
    argv = [sys.executable, os.path.abspath(__file__), lane_flag]
    if smoke:
        argv.append("--smoke")
    if out_path:
        argv += ["--out", out_path]
    return subprocess.run(argv, env=env, cwd=os.getcwd()).returncode


def bench_overlap(smoke: bool = False, out_path: str = None):
    """Interleaved A/B bench of the comm-overlap paths (one process, alternating
    rounds — the contention-fair method BENCH_NORTHSTAR r5 established for the
    shared chip). Emits ONE JSON line and writes ``BENCH_OVERLAP_*.json``.

    Two lanes:
    - primitive GEMM A/B: monolithic vs chunked (uni/bidirectional ring)
      allgather-matmul and matmul-reduce-scatter over a ``tensor``-axis mesh;
    - end-to-end decode A/B: two InferenceEngines (overlap off/on) at tp>=2,
      alternating generate() rounds, decode tokens/sec medians.

    Honesty: on a host with < 2 real chips the bench re-execs onto a virtual
    8-device CPU mesh — that measures harness correctness and bytes-on-wire,
    NOT ICI overlap; ``platform`` in the JSON says which one you got. Chunk
    count == tp, so overlap headroom only exists at tp >= 2.
    """
    import numpy as np

    if os.environ.get("_DS_TPU_BENCH_OVERLAP_CHILD") != "1":
        # child-spawn decision must not touch jax.devices() in THIS process —
        # a dead TPU tunnel makes it block forever (same guard as
        # __graft_entry__.dryrun_multichip). Overlap needs >= 2 devices.
        from deepspeed_tpu.utils.device_probe import probe_device_count
        if probe_device_count() < 2:
            return _respawn_virtual_cpu("_DS_TPU_BENCH_OVERLAP_CHILD",
                                        "--overlap", smoke, out_path)

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.mesh import (AXIS_TENSOR, MeshSpec,
                                             set_global_mesh)
    from deepspeed_tpu.parallel import overlap as ov
    from deepspeed_tpu.utils.comms_logging import (collective_spans,
                                                   spans_overlap_ratio,
                                                   spans_total_bytes)
    from deepspeed_tpu.utils.jax_compat import shard_map

    # largest power of two ≤ device_count (cap 8): the GEMM shapes below are
    # powers of two, so a 6-device host must bench tp=4, not crash on tp=6
    tp = 1 << min(3, jax.device_count().bit_length() - 1)
    mesh = MeshSpec({"tensor": tp}, jax.devices()[:tp])
    on_tpu = jax.default_backend() == "tpu"
    if smoke:
        m, k, n, iters, rounds = 256, 128, 128, 2, 2
    elif on_tpu:
        m, k, n, iters, rounds = 4096, 4096, 4096, 10, 7
    else:
        m, k, n, iters, rounds = 1024, 512, 512, 5, 5
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.default_rng(0)
    xg = jnp.asarray(rng.standard_normal((m, k)), dt)    # AG: rows sharded
    wg = jnp.asarray(rng.standard_normal((k, n)), dt)
    xr = jnp.asarray(rng.standard_normal((m, k)), dt)    # RS: k sharded
    wr = jnp.asarray(rng.standard_normal((k, n)), dt)

    def smap(fn, in_specs, out_specs):
        return jax.jit(shard_map(fn, mesh=mesh.mesh, axis_names={AXIS_TENSOR},
                                 in_specs=in_specs, out_specs=out_specs,
                                 check_vma=False))

    ag_specs = ((P(AXIS_TENSOR, None), P(None, None)), P(None, None))
    rs_specs = ((P(None, AXIS_TENSOR), P(AXIS_TENSOR, None)),
                P(AXIS_TENSOR, None))
    lanes = {
        "ag_monolithic": (smap(lambda a, b: ov.allgather_matmul_monolithic(
            a, b, AXIS_TENSOR, site="gemm.ag_monolithic"), *ag_specs),
            (xg, wg)),
        "ag_chunked": (smap(lambda a, b: ov.chunked_allgather_matmul(
            a, b, AXIS_TENSOR, bidirectional=False, site="gemm.ag_chunked"),
            *ag_specs), (xg, wg)),
        "ag_chunked_bidir": (smap(lambda a, b: ov.chunked_allgather_matmul(
            a, b, AXIS_TENSOR, bidirectional=True,
            site="gemm.ag_chunked_bidir"), *ag_specs), (xg, wg)),
        "rs_monolithic": (smap(lambda a, b: ov.matmul_reduce_scatter_monolithic(
            a, b, AXIS_TENSOR, site="gemm.rs_monolithic"), *rs_specs),
            (xr, wr)),
        "rs_chunked": (smap(lambda a, b: ov.chunked_matmul_reduce_scatter(
            a, b, AXIS_TENSOR, bidirectional=False, site="gemm.rs_chunked"),
            *rs_specs), (xr, wr)),
        "rs_chunked_bidir": (smap(lambda a, b: ov.chunked_matmul_reduce_scatter(
            a, b, AXIS_TENSOR, bidirectional=True,
            site="gemm.rs_chunked_bidir"), *rs_specs), (xr, wr)),
    }
    collective_spans.reset()
    for fn, args in lanes.values():                      # compile outside timing
        jax.block_until_ready(fn(*args))
    gemm_spans = collective_spans.summary()
    times = {name: [] for name in lanes}
    for _ in range(rounds):                              # interleaved rounds
        for name, (fn, args) in lanes.items():
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args)
            jax.block_until_ready(out)
            times[name].append((time.perf_counter() - t0) / iters)
    med = {name: sorted(ts)[len(ts) // 2] for name, ts in times.items()}

    # ---- decode A/B: overlap off vs on, alternating generate() rounds -------
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import gpt2_cfg
    set_global_mesh(None)
    dec_tp = min(2 if smoke else tp, jax.device_count())
    if smoke:
        n_embd, n_layer, n_head, vocab, gen, prompt, dec_rounds = \
            64, 2, 4, 256, 8, 8, 2
    elif on_tpu:
        n_embd, n_layer, n_head, vocab, gen, prompt, dec_rounds = \
            768, 12, 12, 50304, 128, 64, 5
    else:
        # CPU non-smoke validates the harness, not ICI overlap (see `note`):
        # mid-size model so the A/B finishes inside a CI-ish budget
        n_embd, n_layer, n_head, vocab, gen, prompt, dec_rounds = \
            256, 4, 8, 8192, 32, 16, 3
    cfg_kw = dict(vocab_size=vocab, max_seq_len=prompt + gen, n_embd=n_embd,
                  n_layer=n_layer, n_head=n_head)
    dtype_key = "bfloat16" if on_tpu else "float32"
    # decode batch must put >= tp rows through each step or
    # _overlap_dense_eligible rejects chunking in the (b, 1, k) decode body
    # and the A/B compares two identical compiled loops
    dec_batch = 2 * dec_tp
    ids = rng.integers(0, vocab, size=(dec_batch, prompt)).astype(np.int32)
    engines, dec_spans = {}, {}
    for name, enabled in (("decode_monolithic", False), ("decode_overlap", True)):
        engines[name] = ds.init_inference(
            model=gpt2_cfg(**cfg_kw),
            config={"dtype": dtype_key, "max_out_tokens": prompt + gen,
                    "tensor_parallel": {"tp_size": dec_tp},
                    "comm_overlap": {"enabled": enabled}})
        # capture each engine's trace spans separately: blending A and B would
        # make overlap_ratio a property of the harness mix, not of either config
        collective_spans.reset()
        engines[name].generate(ids, max_new_tokens=gen)  # compile
        dec_spans[name] = collective_spans.summary()
    dec_tps = {name: [] for name in engines}
    toks = {}
    for _ in range(dec_rounds):                          # interleaved
        for name, e in engines.items():
            toks[name] = e.generate(ids, max_new_tokens=gen)
            if e.decode_tps:
                dec_tps[name].append(e.decode_tps)
    greedy_match = bool(np.array_equal(toks["decode_monolithic"],
                                       toks["decode_overlap"]))
    dec_med = {name: (sorted(v)[len(v) // 2] if v else None)
               for name, v in dec_tps.items()}

    def ratio(a, b):
        return round(a / b, 4) if (a and b) else None

    result = {
        "metric": "comm_overlap_interleaved_ab",
        "value": ratio(med["ag_monolithic"], med["ag_chunked_bidir"]) or 0.0,
        "unit": "speedup_x (allgather-matmul, chunked-bidir vs monolithic)",
        "vs_baseline": 1.0,
        "platform": jax.devices()[0].platform,
        "device_count": jax.device_count(),
        "tp": tp,
        "gemm_shape": [m, k, n],
        "gemm_ms": {kk: round(v * 1e3, 3) for kk, v in med.items()},
        "speedup": {
            "ag_chunked": ratio(med["ag_monolithic"], med["ag_chunked"]),
            "ag_chunked_bidir": ratio(med["ag_monolithic"],
                                      med["ag_chunked_bidir"]),
            "rs_chunked": ratio(med["rs_monolithic"], med["rs_chunked"]),
            "rs_chunked_bidir": ratio(med["rs_monolithic"],
                                      med["rs_chunked_bidir"]),
        },
        "decode": {"tp": dec_tp, "gen_tokens": gen,
                   "tokens_per_sec": {kk: round(v, 2) if v else None
                                      for kk, v in dec_med.items()},
                   "speedup": ratio(dec_med["decode_overlap"],
                                    dec_med["decode_monolithic"]),
                   "greedy_tokens_match": greedy_match},
        # honesty: bytes/ratio are the OVERLAP engine's decode+prefill traces
        # only (the config a user would deploy); the monolithic engine's and
        # GEMM lanes' spans ride along for comparison
        "bytes_on_wire_per_trace": spans_total_bytes(
            dec_spans["decode_overlap"]),
        "overlap_ratio": round(
            spans_overlap_ratio(dec_spans["decode_overlap"]), 4),
        "collective_spans": {"gemm": gemm_spans, **dec_spans},
        "method": "interleaved A/B in one process (BENCH_NORTHSTAR r5); "
                  "medians over alternating rounds",
        "smoke": bool(smoke),
    }
    if not on_tpu:
        result["note"] = ("virtual CPU mesh: validates the harness and "
                          "bytes-on-wire accounting, NOT ICI overlap — ring "
                          "ppermutes on CPU are memcpy-bound, so chunked can "
                          "measure slower here; judge overlap on tp>=2 TPU")
    out_path = out_path or f"BENCH_OVERLAP_{'smoke' if smoke else 'local'}.json"
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return 0


def bench_wq(smoke: bool = False, out_path: str = None):
    """Interleaved A/B/C bench of weight-streaming quantized decode (``--wq``):
    bf16 vs int8 vs int4 engines on the same weights, alternating generate()
    rounds (the contention-fair method BENCH_NORTHSTAR r5 established). Emits
    ONE JSON line and writes ``BENCH_WQ_*.json``.

    Per lane: decode tokens/sec (generation-length differencing — cancels
    prefill + dispatch RTT exactly), TTFT, greedy-token parity rate vs the
    bf16 lane, and the engine's modeled weight-stream bytes per step
    (``weight_stream_report`` — the fused kernel's own block accounting:
    payload + scales, each block read exactly once).

    Honesty: on a host without a real TPU the bench re-execs onto a virtual
    CPU mesh — decode there runs the XLA fallback path (hoisted whole-tree
    dequant), so tok/s ratios measure harness correctness, NOT HBM streaming;
    the modeled bytes reduction is the meaningful figure (``platform`` says
    which you got). On a TPU the 7B lanes run SEQUENTIALLY (bf16 + int8
    engines do not co-fit in 16 GB HBM); engines share one init seed so
    parity is still apples-to-apples.
    """
    import numpy as np

    if os.environ.get("_DS_TPU_BENCH_WQ_CHILD") != "1":
        # same dead-tunnel guard as --overlap: never jax.devices() in a
        # process that hasn't decided its platform yet. A healthy CPU host
        # runs in-process (the probe already initialised the CPU backend);
        # only a failed probe — dead TPU tunnel — re-execs onto the mesh.
        from deepspeed_tpu.utils.device_probe import probe_device_inventory
        if probe_device_inventory() is None:
            return _respawn_virtual_cpu("_DS_TPU_BENCH_WQ_CHILD", "--wq",
                                        smoke, out_path)

    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import bloom_cfg, gpt2_cfg

    on_tpu = jax.default_backend() == "tpu"
    seq_lanes = on_tpu and not smoke          # 7B lanes don't co-fit in HBM
    if smoke:
        prompt, gen, rounds, batch = 8, 8, 2, 2
        mk_cfg = lambda: gpt2_cfg(vocab_size=256, max_seq_len=prompt + gen,
                                  n_embd=64, n_layer=2, n_head=4)
        dtype_key = "float32"
    elif on_tpu:
        # north-star config 5 shape: 7B weights-dominated single-stream decode
        prompt, gen, rounds, batch = 512, 64, 3, 1
        mk_cfg = lambda: bloom_cfg(vocab_size=250880, max_seq_len=prompt + gen,
                                   n_embd=4096, n_layer=30, n_head=32)
        dtype_key = "bfloat16"
    else:
        prompt, gen, rounds, batch = 16, 32, 3, 4
        mk_cfg = lambda: gpt2_cfg(vocab_size=8192, max_seq_len=prompt + gen,
                                  n_embd=256, n_layer=4, n_head=8)
        dtype_key = "float32"

    lane_cfgs = {
        "bf16": {},
        "int8": {"weight_quant": {"enabled": True, "bits": 8}},
        "int4": {"weight_quant": {"enabled": True, "bits": 4}},
    }
    rng = np.random.default_rng(0)
    vocab = mk_cfg().vocab_size
    ids = rng.integers(0, vocab, size=(batch, prompt)).astype(np.int32)
    short_len = max(4, gen // 4)

    def build(name):
        cfg = {"dtype": dtype_key, "max_out_tokens": prompt + gen,
               **lane_cfgs[name]}
        # engines share the default init seed: identical fp weights before
        # quantization, so greedy parity is a property of the quantization
        return ds.init_inference(model=mk_cfg(), config=cfg)

    def warmup(e):
        _sync(e.generate(ids, max_new_tokens=short_len))
        _sync(e.generate(ids, max_new_tokens=gen))

    def one_round(e):
        t0 = time.perf_counter()
        out = e.generate(ids, max_new_tokens=gen)
        _sync(out)
        dt_long = time.perf_counter() - t0
        ttft = e.ttft
        t0 = time.perf_counter()
        _sync(e.generate(ids, max_new_tokens=short_len))
        dt_short = time.perf_counter() - t0
        per_token = (dt_long - dt_short) / (gen - short_len)
        # differencing can go non-positive when the model is so small that
        # noise dominates (smoke lane) — report None rather than a fake tps
        tps = batch / per_token if per_token > 0 else None
        return tps, ttft, np.asarray(out)[:, prompt:]

    # Greedy-token parity is TEACHER-FORCED: each quant engine's per-step
    # argmax over the bf16 lane's own (prompt + generation) context, compared
    # position-wise against the bf16 argmax. Free-running comparison would
    # compound one near-tie flip into a diverged suffix and report the
    # divergence POINT, not the per-token agreement rate.
    def tf_argmax(e, full):
        return np.asarray(e(full))[:, prompt - 1:-1].argmax(-1)

    parity = {}
    if not seq_lanes:
        engines = {name: build(name) for name in lane_cfgs}
        for e in engines.values():
            warmup(e)
        samples = {name: [] for name in engines}
        toks = {}
        for _ in range(rounds):                          # interleaved
            for name, e in engines.items():
                tps, ttft, t = one_round(e)
                samples[name].append((tps, ttft))
                toks[name] = t
        full = np.concatenate([ids, toks["bf16"]], axis=1)
        ref = tf_argmax(engines["bf16"], full)
        for name, e in engines.items():
            if name != "bf16":
                parity[name] = float((tf_argmax(e, full) == ref).mean())
        reports = {name: (e.weight_stream_report(), e.quant_audit)
                   for name, e in engines.items()}
    else:
        samples, toks, reports = {}, {}, {}
        full = ref = None
        for name in lane_cfgs:                           # sequential: free HBM
            e = build(name)
            warmup(e)
            samples[name] = []
            for _ in range(rounds):
                tps, ttft, t = one_round(e)
                samples[name].append((tps, ttft))
            toks[name] = t
            if name == "bf16":
                full = np.concatenate([ids, toks["bf16"]], axis=1)
                ref = tf_argmax(e, full)
            else:
                parity[name] = float((tf_argmax(e, full) == ref).mean())
            reports[name] = (e.weight_stream_report(), e.quant_audit)
            del e
            import gc
            gc.collect()

    def med(vals):
        s = sorted(vals)
        return s[len(s) // 2] if s else None

    result_lanes = {}
    for name, ss in samples.items():
        tps_med = med([t for t, _ in ss if t])
        ttft_med = med([tt for _, tt in ss if tt])
        rep, audit = reports[name]
        lane = {"decode_tokens_per_sec": round(tps_med, 2) if tps_med else None,
                "ttft_ms": round(ttft_med * 1e3, 2) if ttft_med else None}
        if name != "bf16":
            lane["greedy_parity_vs_bf16"] = round(parity[name], 4)
            lane["modeled_step_bytes"] = rep["modeled_step_bytes"]
            lane["modeled_bytes_reduction_total"] = round(
                rep["reduction_total"], 4)
            lane["modeled_bytes_reduction_quantized_nodes"] = round(
                rep["reduction_quantized_nodes"], 4)
            lane["matrices_quantized"] = sum(
                1 for a in audit if a["decision"] == "quantized")
            lane["matrices_kept_fp"] = sum(
                1 for a in audit if a["decision"] != "quantized")
        result_lanes[name] = lane

    def ratio(a, b):
        return round(a / b, 4) if (a and b) else None

    speedup8 = ratio(result_lanes["int8"]["decode_tokens_per_sec"],
                     result_lanes["bf16"]["decode_tokens_per_sec"])
    result = {
        "metric": "weight_quant_decode_interleaved_ab",
        "value": speedup8 or 0.0,
        "unit": "speedup_x (int8 vs bf16 decode tokens/s)",
        "vs_baseline": 1.0,
        "platform": jax.devices()[0].platform,
        "device_count": jax.device_count(),
        "model": {"prompt": prompt, "gen": gen, "batch": batch,
                  "params": mk_cfg().num_params()},
        "lanes": result_lanes,
        "speedup": {"int8": speedup8,
                    "int4": ratio(result_lanes["int4"]["decode_tokens_per_sec"],
                                  result_lanes["bf16"]["decode_tokens_per_sec"])},
        "acceptance": {
            "int8_greedy_parity_ge_0.98":
                result_lanes["int8"]["greedy_parity_vs_bf16"] >= 0.98,
            "modeled_reduction_int8_ge_1.9x":
                result_lanes["int8"]
                ["modeled_bytes_reduction_quantized_nodes"] >= 1.9,
            "modeled_reduction_int4_ge_3.5x":
                result_lanes["int4"]
                ["modeled_bytes_reduction_quantized_nodes"] >= 3.5,
        },
        "method": ("sequential 7B lanes, shared init seed (engines do not "
                   "co-fit in HBM)" if seq_lanes else
                   "interleaved A/B/C in one process (BENCH_NORTHSTAR r5); "
                   "medians over alternating rounds"),
        "smoke": bool(smoke),
    }
    if seq_lanes:
        # the 1.4x criterion applies to the 7B weights-dominated lane only —
        # a tiny-model TPU smoke's differencing is noise, not a measurement
        result["acceptance"]["int8_decode_speedup_ge_1.4x"] = \
            bool(speedup8 and speedup8 >= 1.4)
    if not on_tpu:
        result["note"] = (
            "virtual CPU mesh: decode runs the XLA fallback (hoisted "
            "whole-tree dequant), so tok/s ratios do NOT measure HBM weight "
            "streaming — judge int8/int4 wins by the modeled bytes-per-step "
            "reduction (kernel block accounting) until a TPU chip is "
            "reachable")
    out_path = out_path or f"BENCH_WQ_{'smoke' if smoke else 'local'}.json"
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return 0


def bench_qring(smoke: bool = False, out_path: str = None):
    """Interleaved A/B/C bench of the fused quantized collective-matmul ring
    (``--qring``) at tp=4: (A) monolithic-psum quantized decode — the ground
    truth the ring must match, (B) fp ring (comm_overlap on, fp weights),
    (C) fused quantized ring (int8 weights + int8 EF wire). Emits ONE JSON
    line and writes ``BENCH_QRING_*.json``.

    Gates (in-file): teacher-forced greedy parity of the quantized ring vs
    the monolithic-psum quantized engine >= 0.98 (the ``--wq`` method:
    per-step argmax over lane A's own context — free-running comparison
    would compound one near-tie flip and report the divergence POINT, not
    the per-token agreement rate; the free-running match bool is recorded
    honestly alongside); modeled ring bytes quantized/fp32 <= 0.3; and the
    modeled numbers are never hand-computed — the recorded span, the closed
    form ``analysis.collectives.qring_wire_bytes``, and the jaxpr
    ppermute-operand sum must agree to the byte (``crosscheck.exact``).

    Honesty: without a real TPU the bench re-execs onto a virtual 8-device
    CPU mesh and FORCES the fused backend (``DS_TPU_WQ_FORCE_FUSED=1``) —
    otherwise the engine's hoisted whole-tree dequant means quant nodes
    never reach the ring at all. Kernels then run in Pallas interpret mode,
    so tok/s ratios measure harness correctness, NOT ICI overlap or MXU
    throughput; judge the quantized ring by bytes-on-wire + parity until a
    chip is reachable (``platform`` says which you got).
    """
    import numpy as np

    if os.environ.get("_DS_TPU_BENCH_QRING_CHILD") != "1":
        # same dead-tunnel guard as --overlap: no jax.devices() before the
        # platform is decided. The ring A/B needs tp=4.
        from deepspeed_tpu.utils.device_probe import probe_device_count
        if probe_device_count() < 4:
            return _respawn_virtual_cpu("_DS_TPU_BENCH_QRING_CHILD",
                                        "--qring", smoke, out_path)

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        os.environ["DS_TPU_WQ_FORCE_FUSED"] = "1"

    import deepspeed_tpu as ds
    from deepspeed_tpu.analysis.collectives import (crosscheck_findings,
                                                    qring_wire_bytes)
    from deepspeed_tpu.models import gpt2_cfg
    from deepspeed_tpu.ops.quantizer.quant import quantize_grouped
    from deepspeed_tpu.parallel import qring as qr
    from deepspeed_tpu.parallel.mesh import (AXIS_TENSOR, MeshSpec,
                                             set_global_mesh)
    from deepspeed_tpu.utils.comms_logging import collective_spans
    from deepspeed_tpu.utils.jax_compat import shard_map

    tp = 4
    if jax.device_count() < tp:
        print(json.dumps({"metric": "qring_interleaved_ab", "value": 0.0,
                          "unit": "error", "error": "needs >= 4 devices"}))
        return 1
    if smoke:
        n_embd, n_layer, n_head, vocab, gen, prompt, rounds = \
            64, 2, 4, 256, 8, 8, 2
    elif on_tpu:
        n_embd, n_layer, n_head, vocab, gen, prompt, rounds = \
            768, 12, 12, 50304, 64, 32, 5
    else:
        # CPU non-smoke: interpret-mode kernels — keep the model small
        # enough that three engines compile inside a CI-ish budget
        n_embd, n_layer, n_head, vocab, gen, prompt, rounds = \
            128, 2, 4, 2048, 16, 16, 3
    batch = 2 * tp          # >= tp rows per decode step or the ring is
    qblock = 64             # ineligible and the A/B compares identical loops
    wq = {"enabled": True, "bits": 8, "group": 16}
    dtype_key = "bfloat16" if on_tpu else "float32"
    cfg_kw = dict(vocab_size=vocab, max_seq_len=prompt + gen, n_embd=n_embd,
                  n_layer=n_layer, n_head=n_head)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=(batch, prompt)).astype(np.int32)

    lane_cfgs = {
        "mono_quant": {"weight_quant": wq,
                       "comm_overlap": {"enabled": False}},
        "fp_ring": {"comm_overlap": {"enabled": True}},
        "qring": {"weight_quant": wq,
                  "comm_overlap": {"enabled": True, "chunk_bits": 8,
                                   "quant_block": qblock}},
    }
    engines, spans = {}, {}
    for name, extra in lane_cfgs.items():
        engines[name] = ds.init_inference(
            model=gpt2_cfg(**cfg_kw),
            config={"dtype": dtype_key, "max_out_tokens": prompt + gen,
                    "tensor_parallel": {"tp_size": tp}, **extra})
        # per-engine trace spans: blending lanes would make the byte ratio a
        # property of the harness mix, not of either config
        collective_spans.reset()
        engines[name].generate(ids, max_new_tokens=gen)      # compile
        spans[name] = collective_spans.summary()

    tps = {name: [] for name in engines}
    toks = {}
    for _ in range(rounds):                                  # interleaved
        for name, e in engines.items():
            toks[name] = e.generate(ids, max_new_tokens=gen)
            if e.decode_tps:
                tps[name].append(e.decode_tps)
    med = {name: (sorted(v)[len(v) // 2] if v else None)
           for name, v in tps.items()}
    greedy_match = bool(np.array_equal(toks["mono_quant"], toks["qring"]))

    # teacher-forced parity (the --wq method), quantized ring vs
    # monolithic-psum quantized ground truth
    full = np.concatenate([ids, np.asarray(toks["mono_quant"])], axis=1)

    def tf_argmax(e):
        return np.asarray(e(full))[:, prompt - 1:-1].argmax(-1)

    parity = float((tf_argmax(engines["qring"])
                    == tf_argmax(engines["mono_quant"])).mean())

    def ring_bytes(summary):
        # the overlapped ring legs only; the fp all-gather legs are byte-
        # identical across lanes and the monolithic lane has no ring at all
        return sum(rec["bytes_total"] for rec in summary.values()
                   if rec.get("op") == "reduce_scatter")

    rec_ratio = (ring_bytes(spans["qring"]) / ring_bytes(spans["fp_ring"])
                 if ring_bytes(spans["fp_ring"]) else None)

    # machine cross-check at the decode-step o_proj ring shape: the span,
    # the closed form, and the jaxpr must agree to the byte — only then do
    # the modeled numbers below count
    mesh = MeshSpec({"tensor": tp}, jax.devices()[:tp])
    xs = jnp.asarray(rng.standard_normal((batch, n_embd)), jnp.float32)
    qw, sw = quantize_grouped(
        jnp.asarray(rng.standard_normal((n_embd, n_embd)), jnp.float32),
        group_size=wq["group"], bits=8)

    def mk(wb, site):
        def body(a, b, c):
            out, _ = qr.fused_quant_matmul_reduce_scatter(
                a, b, c, AXIS_TENSOR, bits=8, wire_bits=wb,
                quant_block=qblock, site=site)
            return out
        return shard_map(body, mesh=mesh.mesh, axis_names={AXIS_TENSOR},
                         in_specs=(P(None, AXIS_TENSOR),
                                   P(AXIS_TENSOR, None),
                                   P(AXIS_TENSOR, None)),
                         out_specs=P(AXIS_TENSOR, None), check_vma=False)

    crosscheck = {"exact": True}
    for wb, label in ((8, "int8_wire"), (None, "fp32_wire")):
        site = f"bench.qring_{label}"
        before = collective_spans.summary().get(site, {}).get(
            "bytes_total", 0)
        res = crosscheck_findings(mk(wb, site), (xs, qw, sw),
                                  site_prefixes=("bench.",), target=site)
        recorded = collective_spans.summary().get(site, {}).get(
            "bytes_total", 0) - before
        closed = qring_wire_bytes(batch, n_embd, tp, wire_bits=wb,
                                  block=qblock)
        n_err = sum(1 for f in res.findings if f.severity == "error")
        crosscheck[label] = {"recorded_span_bytes": int(recorded),
                             "closed_form_bytes": int(closed),
                             "jaxpr_error_findings": n_err}
        crosscheck["exact"] = bool(crosscheck["exact"]
                                   and recorded == closed and not n_err)
    modeled_ratio = (crosscheck["int8_wire"]["closed_form_bytes"]
                     / crosscheck["fp32_wire"]["closed_form_bytes"])

    def ratio(a, b):
        return round(a / b, 4) if (a and b) else None

    gates = {
        "tf_parity_qring_vs_mono_ge_0.98": parity >= 0.98,
        "modeled_ring_bytes_ratio_le_0.3": modeled_ratio <= 0.3,
        "recorded_engine_ring_bytes_ratio_le_0.3":
            rec_ratio is not None and rec_ratio <= 0.3,
        "crosscheck_exact": bool(crosscheck["exact"]),
    }
    result = {
        "metric": "qring_interleaved_ab",
        "value": round(modeled_ratio, 4),
        "unit": "ring bytes-on-wire, quantized/fp32 (gate <= 0.3)",
        "vs_baseline": 1.0,
        "platform": jax.devices()[0].platform,
        "device_count": jax.device_count(),
        "tp": tp,
        "model": {"prompt": prompt, "gen": gen, "batch": batch,
                  "n_embd": n_embd, "n_layer": n_layer},
        "wire": {"chunk_bits": 8, "quant_block": qblock,
                 "weight_bits": wq["bits"], "weight_group": wq["group"]},
        "decode_tokens_per_sec": {name: round(v, 2) if v else None
                                  for name, v in med.items()},
        "speedup_qring_vs_mono": ratio(med["qring"], med["mono_quant"]),
        "tf_greedy_parity_qring_vs_mono": round(parity, 4),
        "greedy_tokens_match_free_running": greedy_match,
        "ring_bytes_recorded": {name: ring_bytes(spans[name])
                                for name in spans},
        "ring_bytes_ratio_recorded": round(rec_ratio, 4)
        if rec_ratio is not None else None,
        "crosscheck": crosscheck,
        "qring_gates": gates,
        "collective_spans": spans,
        "method": "interleaved A/B/C in one process (BENCH_NORTHSTAR r5); "
                  "medians over alternating rounds; parity teacher-forced",
        "smoke": bool(smoke),
    }
    if not on_tpu:
        result["note"] = (
            "virtual CPU mesh, DS_TPU_WQ_FORCE_FUSED=1: interpret-mode "
            "kernels — tok/s ratios validate the harness, NOT ICI overlap "
            "or MXU throughput; the gated figures are parity and the "
            "cross-checked bytes-on-wire model")
    set_global_mesh(None)
    out_path = out_path or f"BENCH_QRING_{'smoke' if smoke else 'local'}.json"
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    return 0


def bench_trajectory(root: str = ".", out_json: str = "BENCH_TRAJECTORY.json",
                     out_md: str = "BENCH_TRAJECTORY.md") -> dict:
    """Scrape every ``BENCH_*.json`` headline + gate verdict into ONE
    machine-readable perf record (``--trajectory``).

    The per-PR bench artifacts carry their own shapes (``metric``/``value``
    headlines, ``*_gates`` dicts with in-file booleans, the round-1 wrapper's
    nested ``parsed``, the NORTHSTAR ``results`` lists); this walks them all
    tolerantly and emits one row per artifact — file, PR round (from the
    ``_rNN`` suffix), headline metric, gate pass-count, and overall verdict —
    plus a markdown table, so "is the perf record still green, and what did
    each PR claim?" is one file instead of fifteen."""
    import glob
    import re as _re
    rows = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_*.json"))):
        name = os.path.basename(path)
        if name.startswith("BENCH_TRAJECTORY"):
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"file": name, "error": f"{type(e).__name__}: {e}"})
            continue
        m = _re.search(r"_r(\d+)", name)
        head = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
            else doc
        if head.get("metric") is None and isinstance(doc.get("results"),
                                                     list):
            # NORTHSTAR shape: a list of measurement dicts — headline on the
            # first, count the rest
            results = [r for r in doc["results"] if isinstance(r, dict)]
            head = results[0] if results else {}
        gates = None
        for key in sorted(doc):
            if (key.endswith("gates") or key == "acceptance") \
                    and isinstance(doc[key], dict):
                gates = doc[key]
                break
        n_true = n_bool = 0
        if gates is not None:
            for v in gates.values():
                if isinstance(v, bool):
                    n_bool += 1
                    n_true += int(v)
        gates_ok = doc.get("gates_ok")
        if gates_ok is None and n_bool:
            gates_ok = n_true == n_bool
        rows.append({
            "file": name,
            "round": int(m.group(1)) if m else None,
            "metric": head.get("metric"),
            "value": head.get("value"),
            "unit": head.get("unit"),
            "smoke": doc.get("smoke"),
            "gates_true": n_true if n_bool else None,
            "gates_total": n_bool if n_bool else None,
            "gates_ok": gates_ok,
        })
    rows.sort(key=lambda r: (r.get("round") is None, r.get("round") or 0,
                             r["file"]))
    md_lines = [
        "# Bench trajectory",
        "",
        "One row per committed `BENCH_*.json` artifact "
        "(regenerate with `python bench.py --trajectory`).",
        "",
        "| file | round | metric | value | unit | smoke | gates | ok |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        if "error" in r:
            md_lines.append(f"| {r['file']} |  | (unreadable: {r['error']}) "
                            "|  |  |  |  |  |")
            continue
        val = r["value"]
        val = f"{val:.4g}" if isinstance(val, (int, float)) else (val or "")
        gates = (f"{r['gates_true']}/{r['gates_total']}"
                 if r["gates_total"] else "")
        ok = {True: "✓", False: "✗", None: ""}[r["gates_ok"]]
        md_lines.append(
            f"| {r['file']} | {r['round'] if r['round'] is not None else ''} "
            f"| {r['metric'] or ''} | {val} | {r['unit'] or ''} "
            f"| {'y' if r['smoke'] else ''} | {gates} | {ok} |")
    md = "\n".join(md_lines) + "\n"
    # the "## Tier-1 window" section is hand-maintained (one line per PR's
    # measured dots/870s — ROADMAP's carried maintenance item); carry it
    # across regenerations instead of clobbering it with the table
    md_path = os.path.join(root, out_md)
    if os.path.exists(md_path):
        with open(md_path) as f:
            prev = f.read()
        marker = prev.find("## Tier-1 window")
        if marker >= 0:
            md += "\n" + prev[marker:].rstrip() + "\n"
    out = {"metric": "bench_trajectory", "artifacts": len(rows),
           # an unreadable artifact is a broken perf record, not a pass;
           # gate-less old artifacts (gates_ok None) still count as ok
           "all_gates_ok": all(r.get("gates_ok") is not False
                               and "error" not in r for r in rows),
           "rows": rows}
    with open(os.path.join(root, out_json), "w") as f:
        json.dump(out, f, indent=1)
    with open(os.path.join(root, out_md), "w") as f:
        f.write(md)
    print(json.dumps({"metric": "bench_trajectory", "artifacts": len(rows),
                      "out": out_json, "md": out_md,
                      "all_gates_ok": out["all_gates_ok"]}))
    return out


_KERNEL_GATE = None


def _with_gate(out: dict) -> dict:
    if _KERNEL_GATE is not None:
        out["kernels_ok"] = True
        out["kernel_max_abs_err"] = {k: round(v, 5)
                                     for k, v in _KERNEL_GATE.items()}
    return out


def main():
    global _KERNEL_GATE
    p = argparse.ArgumentParser()
    p.add_argument("--mode", choices=["train", "inference"], default=None,
                   help="defaults to the mode the chosen --model implies")
    p.add_argument("--model", choices=["default", "1.3b", "7b"], default="default",
                   help="north-star shapes: --model 1.3b (train, BASELINE config 3) "
                        "or --model 7b (inference, BASELINE config 5)")
    p.add_argument("--skip-kernel-gate", action="store_true",
                   help="skip the compiled-kernel pre-check (debugging only)")
    p.add_argument("--overlap", action="store_true",
                   help="interleaved A/B bench of the comm-overlap paths "
                        "(chunked collective matmuls vs monolithic); emits "
                        "BENCH_OVERLAP_*.json")
    p.add_argument("--wq", action="store_true",
                   help="interleaved A/B/C bench of weight-streaming "
                        "quantized decode (bf16 vs int8 vs int4: decode "
                        "tok/s, greedy parity, modeled bytes-per-step); "
                        "emits BENCH_WQ_*.json")
    p.add_argument("--qring", action="store_true",
                   help="interleaved A/B/C bench of the fused quantized "
                        "collective-matmul ring (monolithic-psum quantized vs "
                        "fp ring vs int8-wire quantized ring: teacher-forced "
                        "greedy parity, machine-cross-checked bytes-on-wire "
                        "ratio); emits BENCH_QRING_*.json")
    p.add_argument("--smoke", action="store_true",
                   help="with --overlap/--wq/--qring: tiny shapes, CPU-safe — "
                        "asserts the A/B harness runs and the JSON is valid")
    p.add_argument("--trajectory", action="store_true",
                   help="scrape every BENCH_*.json gate/headline into "
                        "BENCH_TRAJECTORY.json + a markdown table (the "
                        "machine-readable per-PR perf record); runs offline, "
                        "no model builds")
    p.add_argument("--out", default=None,
                   help="with --overlap/--wq/--qring: output JSON path")
    args = p.parse_args()
    if args.trajectory:
        bench_trajectory()
        return 0
    if args.smoke and not (args.overlap or args.wq or args.qring):
        p.error("--smoke requires --overlap, --wq or --qring")
    if sum((args.overlap, args.wq, args.qring)) > 1:
        p.error("--overlap/--wq/--qring are separate lanes; pick one")
    if args.overlap:
        return bench_overlap(smoke=args.smoke, out_path=args.out)
    if args.wq:
        return bench_wq(smoke=args.smoke, out_path=args.out)
    if args.qring:
        return bench_qring(smoke=args.smoke, out_path=args.out)
    if args.model == "1.3b" and args.mode == "inference":
        p.error("--model 1.3b is a training benchmark")
    if args.model == "7b" and args.mode == "train":
        p.error("--model 7b is an inference benchmark")
    mode = "inference" if args.model == "7b" or args.mode == "inference" \
        else "train"
    if not args.skip_kernel_gate:
        try:
            _KERNEL_GATE = kernel_gate(mode)
        except Exception as e:
            print(json.dumps({"metric": "kernel_gate", "value": 0.0, "unit": "ok",
                              "vs_baseline": 0.0, "kernels_ok": False,
                              "error": str(e)}))
            return 1
    if args.model == "1.3b":
        bench_train_13b()
    elif args.model == "7b":
        bench_inference_7b()
    elif mode == "train":
        bench_train()
    else:
        bench_inference()


if __name__ == "__main__":
    sys.exit(main())
